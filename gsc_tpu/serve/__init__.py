"""gsc_tpu.serve — AOT-compiled policy serving with request micro-batching.

The production inference path (ROADMAP item 4): ``cli serve`` / the
programmatic :class:`PolicyServer` answer coordination requests from an
ahead-of-time compiled greedy policy (per batch-size bucket, persisted in
an on-disk artifact cache keyed by checkpoint fingerprint), folding
concurrent requests into padded device batches with a deadline flush, and
streaming p50/p99 latency through the run's MetricsHub.  Without a
checkpoint the SPR shortest-path heuristic serves as the non-learned
fallback tier.
"""
from .batcher import BATCH_MODES, MicroBatcher, ServeError, ServeFuture
from .cache import ArtifactCache, cache_material
from .fallback import SPRFallbackPolicy, spr_schedule_action
from .fleet import (FleetDispatcher, VersionWatcher, WeightPublisher,
                    params_fingerprint)
from .policy import (GreedyServePolicy, ObsTemplate, exec_fn_name,
                     policy_fn_name)
from .server import PolicyServer

__all__ = [
    "ArtifactCache", "BATCH_MODES", "FleetDispatcher", "GreedyServePolicy",
    "MicroBatcher", "ObsTemplate", "PolicyServer", "SPRFallbackPolicy",
    "ServeError", "ServeFuture", "VersionWatcher", "WeightPublisher",
    "cache_material", "exec_fn_name", "params_fingerprint",
    "policy_fn_name", "spr_schedule_action",
]
