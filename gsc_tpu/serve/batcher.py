"""Request micro-batcher: thread-safe queue + deadline-based flusher.

Concurrent coordination requests land on a bounded queue; one batcher
thread folds them into bucketed device batches:

- a flush fires when the OLDEST queued request has waited ``deadline_ms``
  or the largest bucket is full, whichever comes first — so a lone request
  pays at most the deadline, and a burst amortizes one device call;
- the flushed batch runs in the smallest configured bucket that fits it,
  padded by repeating the last real request (see
  ``ObsTemplate.stack_pad``); answers are sliced back per request.

Each request's answer is bit-identical regardless of batch-mates: the
bucketed policy is a ``vmap`` over the request axis, so rows never
interact (test-asserted padding-invariance).  Latency accounting flows
through the shared :class:`~gsc_tpu.obs.MetricsHub`:

- ``serve_latency_ms`` histogram (overall and tagged per bucket),
- ``serve_batch_ms`` device-call histogram per bucket,
- ``serve_requests_total`` / ``serve_batches_total{bucket=..}`` counters,
- ``serve_rejected_total{reason=queue_full|stopping}`` for overload
  rejections (counted BEFORE the ServeError reaches the caller, so
  rejected load is visible in telemetry, not only in client stacks),
- ``serve_queue_depth`` gauge sampled at every submit AND every flush
  (submit-side sampling keeps it honest between flushes and while idle).

Request-path tracing: every request carries a monotonically increasing
``trace_id`` and is stamped at enqueue, batch admission (popped off the
queue into a forming batch), device dispatch and completion.  With a
:class:`~gsc_tpu.obs.slo.ServeTracer` attached, ``_flush`` hands the
stamped batch over as ONE compact record (a deque append of plain
floats — the flush path does timestamps + deferred emission only, no
derived math, no I/O); the tracer's drainer thread later decomposes
``serve_latency_ms`` into queue-wait / batch-formation wait / device
wall / fan-out, feeds the SLO engine and emits the span events.  With
``tracer=None`` the batcher behaves byte-for-byte as before.

The batcher is transport-agnostic: ``submit`` is the in-process API
(``PolicyServer`` wraps it); an RPC front-end would call the same method.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .policy import ObsTemplate


class ServeError(RuntimeError):
    """The device call answering this request failed (the error is
    replicated into every affected request's future)."""


class ServeFuture:
    """Minimal future for one request: blocks on ``result`` until the
    batcher fills it (or raises what the device call raised).

    Span timestamps (``time.perf_counter`` for intervals, one wall-clock
    ``time.time`` at enqueue for trace geometry) are stamped as the
    request moves: enqueue here, batch admission in the consumer loop,
    completion after the device result fans out.  Stamping is
    unconditional — timestamps are the only work the tracing contract
    allows on the serve path, and they cost nanoseconds."""

    __slots__ = ("_event", "_result", "_error", "t_enqueued",
                 "wall_enqueued", "t_admitted", "t_completed", "trace_id")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self.t_enqueued = time.perf_counter()
        self.wall_enqueued = time.time()
        self.t_admitted: Optional[float] = None
        self.t_completed: Optional[float] = None
        self.trace_id: int = -1

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still queued after "
                               f"{timeout}s")
        if self._error is not None:
            raise ServeError(str(self._error)) from self._error
        return self._result


_STOP = object()


class MicroBatcher:
    """One consumer thread over a bounded request queue.

    ``run_batch(leaves, n_real, bucket) -> np.ndarray [bucket, A]`` is the
    execution backend (the server provides the AOT-compiled device call or
    the fallback tier); ``leaves`` are the bucket-stacked obs arrays.
    """

    def __init__(self, run_batch: Callable, template: ObsTemplate,
                 buckets: Sequence[int] = (1, 4, 8),
                 deadline_ms: float = 5.0, hub=None,
                 max_queue: int = 4096,
                 on_flush: Optional[Callable[[int, int], None]] = None,
                 tracer=None):
        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be positive ints: {buckets!r}")
        self.run_batch = run_batch
        self.template = template
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.deadline_s = float(deadline_ms) / 1e3
        self.hub = hub
        self.on_flush = on_flush
        # obs.slo.ServeTracer (or None): receives one compact record per
        # flush + rejection notes; all span math/emission happens on ITS
        # drainer thread, never here
        self.tracer = tracer
        self._next_trace_id = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        # serializes submit's check+enqueue against stop's flag+sentinel:
        # an accepted request is therefore ALWAYS queued ahead of _STOP,
        # so it is served by the drain — without this, a submit that
        # passed the flag check could enqueue after the consumer exited
        # and its future would hang until the client timeout
        self._submit_lock = threading.Lock()

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="gsc-serve-batcher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        """Drain-then-stop: requests queued before the stop are still
        answered; a ``submit`` racing it either lands ahead of the stop
        sentinel (and is served) or raises ServeError at the call site —
        never a silent until-timeout hang (the submit lock makes those
        the only two outcomes)."""
        if self._thread is None:
            return
        with self._submit_lock:
            self._stopping = True
            self._q.put(_STOP)
        self._thread.join(timeout)
        self._thread = None

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    # -------------------------------------------------------------- submit
    def submit(self, obs) -> ServeFuture:
        """Enqueue one request (any obs pytree matching the template).
        Template validation happens HERE, in the caller's thread — a
        malformed request raises at the call site and never reaches the
        shared device path.  A rejection (stopping / queue full) bumps
        ``serve_rejected_total{reason=..}`` BEFORE raising, so overload
        shows up in serve_stats and /metrics instead of vanishing into
        client-side exceptions."""
        leaves = self.template.flatten(obs)
        fut = ServeFuture()
        with self._submit_lock:
            if self._stopping:
                self._note_rejection("stopping", fut)
                raise ServeError("batcher is stopping — request rejected")
            fut.trace_id = self._next_trace_id
            self._next_trace_id += 1
            try:
                self._q.put_nowait((fut, leaves))
            except queue.Full:
                self._note_rejection("queue_full", fut)
                raise ServeError(
                    f"serve queue full ({self._q.maxsize} requests) — "
                    "backpressure: retry or add capacity")
        # live depth between flushes: the flush-side sample alone reads
        # stale while requests pile up or the queue sits idle
        if self.hub is not None:
            self.hub.gauge("serve_queue_depth", self._q.qsize())
        return fut

    def _note_rejection(self, reason: str, fut: ServeFuture):
        if self.hub is not None:
            self.hub.counter("serve_rejected_total", reason=reason)
            self.hub.gauge("serve_queue_depth", self._q.qsize())
        if self.tracer is not None:
            self.tracer.note_rejection(reason, fut.wall_enqueued)

    # ---------------------------------------------------------------- loop
    def _loop(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                break
            item[0].t_admitted = time.perf_counter()
            batch: List[Tuple[ServeFuture, List[np.ndarray]]] = [item]
            deadline = item[0].t_enqueued + self.deadline_s
            stop_after = False
            while len(batch) < self.buckets[-1]:
                remaining = deadline - time.perf_counter()
                try:
                    if remaining <= 0:
                        # deadline already spent (e.g. the previous device
                        # call outlasted it): still DRAIN what is already
                        # queued, non-blocking — otherwise overload
                        # degenerates to bucket-1 flushes exactly when
                        # batching matters most
                        nxt = self._q.get_nowait()
                    else:
                        nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                nxt[0].t_admitted = time.perf_counter()
                batch.append(nxt)
            self._flush(batch)
            if stop_after:
                break
        # backstop: the submit lock means no future can land behind the
        # stop sentinel, but fail anything that somehow did (e.g. a second
        # _STOP from a double stop()) instead of hanging its client
        while True:
            try:
                leftover = self._q.get_nowait()
            except queue.Empty:
                return
            if leftover is _STOP:
                continue
            fut, _ = leftover
            fut._error = ServeError("batcher stopped before this request "
                                    "was served")
            fut._event.set()

    def _flush(self, batch):
        k = len(batch)
        bucket = next(b for b in self.buckets if b >= k)
        stacked = self.template.stack_pad([leaves for _, leaves in batch],
                                          bucket)
        wall_dispatch = time.time()
        t0 = time.perf_counter()
        try:
            out = self.run_batch(stacked, k, bucket)
        except BaseException as e:  # noqa: BLE001 - replicate into futures
            for fut, _ in batch:
                fut._error = e
                fut._event.set()
            if self.hub is not None:
                self.hub.counter("serve_errors_total")
            if self.tracer is not None:
                # a failed device call must BURN the SLO budget, not
                # vanish from it: the engine counts these requests as
                # deadline misses / objective violations (they were
                # never answered), so attainment and the gated slo_*
                # metrics degrade with real serving failures
                self.tracer.record_flush({
                    "bucket": bucket, "n_real": k,
                    "wall_dispatch": wall_dispatch,
                    "t_dispatch": t0,
                    "t_device_done": time.perf_counter(),
                    "queue_depth": self._q.qsize(),
                    "error": f"{type(e).__name__}: {e}",
                    "requests": [(fut.trace_id, fut.wall_enqueued,
                                  fut.t_enqueued, fut.t_admitted, None)
                                 for fut, _ in batch],
                })
            return
        now = time.perf_counter()
        out = np.asarray(out)
        for i, (fut, _) in enumerate(batch):
            fut._result = out[i]
            if self.hub is not None:
                lat_ms = (now - fut.t_enqueued) * 1e3
                self.hub.observe("serve_latency_ms", lat_ms)
                self.hub.observe("serve_latency_ms", lat_ms,
                                 bucket=bucket)
            fut._event.set()
            fut.t_completed = time.perf_counter()
        if self.hub is not None:
            self.hub.counter("serve_requests_total", k)
            self.hub.counter("serve_batches_total", bucket=bucket)
            self.hub.observe("serve_batch_ms", (now - t0) * 1e3,
                             bucket=bucket)
            self.hub.gauge("serve_queue_depth", self._q.qsize())
        if self.tracer is not None:
            # deferred span emission: hand over the raw timestamps as one
            # record (plain floats, O(batch) appends) — the tracer's
            # drainer thread derives the queue/batch/device/fan-out
            # decomposition and emits the events off this thread.
            # `now` doubles as the device-done stamp, so the tracer's
            # reconstructed latency equals the serve_latency_ms values
            # recorded above exactly.
            self.tracer.record_flush({
                "bucket": bucket, "n_real": k,
                "wall_dispatch": wall_dispatch,
                "t_dispatch": t0, "t_device_done": now,
                "queue_depth": self._q.qsize(),
                "requests": [(fut.trace_id, fut.wall_enqueued,
                              fut.t_enqueued, fut.t_admitted,
                              fut.t_completed)
                             for fut, _ in batch],
            })
        if self.on_flush is not None:
            self.on_flush(k, bucket)
