"""Request micro-batcher: thread-safe queue + two batching disciplines.

Concurrent coordination requests land on a bounded queue; the batcher
folds them into bucketed device batches under one of two modes:

- ``mode="deadline"`` (default, the historic discipline): one consumer
  thread; a flush fires when the OLDEST queued request has waited
  ``deadline_ms`` or the largest bucket is full, whichever comes first —
  a lone request pays at most the deadline, a burst amortizes one device
  call;
- ``mode="continuous"``: requests NEVER wait out a deadline.  The
  consumer thread admits whatever is queued, stacks it into the next
  batch, and hands the prepared batch to a dedicated dispatcher thread
  whose only job is running device calls back to back — so the next
  batch is formed (stacked + padded) *while* the current device call is
  in flight, and dispatch happens the moment the device frees.  Under
  load the backlog that accumulates during an in-flight call becomes the
  next batch; at low rate a lone request dispatches immediately instead
  of idling a deadline away.  A single serial client therefore gets
  bucket-for-bucket the same device calls as deadline mode (bit-identical
  answers, test-asserted); the two modes differ only in scheduling.

Either way the flushed batch runs in the smallest configured bucket that
fits it, padded by repeating the last real request (see
``ObsTemplate.stack_pad``); answers are sliced back per request.

Each request's answer is bit-identical regardless of batch-mates: the
bucketed policy is a ``vmap`` over the request axis, so rows never
interact (test-asserted padding-invariance).  Latency accounting flows
through the shared :class:`~gsc_tpu.obs.MetricsHub`:

- ``serve_latency_ms`` histogram (overall and tagged per bucket),
- ``serve_batch_ms`` device-call histogram per bucket,
- ``serve_requests_total`` / ``serve_batches_total{bucket=..}`` counters,
- ``serve_rejected_total{reason=queue_full|stopping}`` for overload
  rejections (counted BEFORE the ServeError reaches the caller, so
  rejected load is visible in telemetry, not only in client stacks),
- ``serve_queue_depth`` gauge sampled at every submit AND every flush
  (submit-side sampling keeps it honest between flushes and while idle).

Fleet mode: with ``worker=`` set (a fleet worker id), the queue-depth
gauge moves to a ``worker=``-tagged series — N workers sharing one hub
must not fight over a single gauge — and per-worker
``serve_requests_total{worker=..}`` / ``serve_batches_total{worker=..}``
counters land NEXT TO the untagged fleet aggregates (the untagged
histograms/counters deliberately stay shared: fleet-wide p50/p99 and
totals come for free).

Hot-swap: every device dispatch runs under ``flush_lock``, and the
version the ``version_provider`` callable reports is read under that
same lock — a :class:`~gsc_tpu.serve.fleet.VersionWatcher` swapping the
served weights acquires ``flush_lock`` first, so a swap lands strictly
BETWEEN device calls: no batch ever mixes policy versions, and the
``policy_version`` stamped on the flush record / futures / span events
is exactly the version the device call read.

Request-path tracing: every request carries a monotonically increasing
``trace_id`` and is stamped at enqueue, batch admission (popped off the
queue into a forming batch), device dispatch and completion.  With a
:class:`~gsc_tpu.obs.slo.ServeTracer` attached, each dispatch hands the
stamped batch over as ONE compact record (a deque append of plain
floats — the dispatch path does timestamps + deferred emission only, no
derived math, no I/O); the tracer's drainer thread later decomposes
``serve_latency_ms`` into queue-wait / batch-formation wait / device
wall / fan-out, feeds the SLO engine and emits the span events.  With
``tracer=None`` the batcher behaves byte-for-byte as before.

The batcher is transport-agnostic: ``submit`` is the in-process API
(``PolicyServer`` wraps it); an RPC front-end would call the same method.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .policy import ObsTemplate

BATCH_MODES = ("deadline", "continuous")


class ServeError(RuntimeError):
    """The device call answering this request failed (the error is
    replicated into every affected request's future)."""


class ServeFuture:
    """Minimal future for one request: blocks on ``result`` until the
    batcher fills it (or raises what the device call raised).

    Span timestamps (``time.perf_counter`` for intervals, one wall-clock
    ``time.time`` at enqueue for trace geometry) are stamped as the
    request moves: enqueue here, batch admission in the consumer loop,
    completion after the device result fans out.  Stamping is
    unconditional — timestamps are the only work the tracing contract
    allows on the serve path, and they cost nanoseconds.  Every stamp a
    done future exposes is written BEFORE ``_event.set()``: a waiter (or
    a racing reader building a trace record) that observes ``done()``
    must never see a half-stamped future."""

    __slots__ = ("_event", "_result", "_error", "t_enqueued",
                 "wall_enqueued", "t_admitted", "t_completed", "trace_id",
                 "policy_version")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self.t_enqueued = time.perf_counter()
        self.wall_enqueued = time.time()
        self.t_admitted: Optional[float] = None
        self.t_completed: Optional[float] = None
        self.trace_id: int = -1
        # the policy version whose device call answered this request
        # (stamped under the flush lock at dispatch; None when the
        # backend declares no versions — raw MicroBatcher use)
        self.policy_version: Optional[int] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still queued after "
                               f"{timeout}s")
        if self._error is not None:
            raise ServeError(str(self._error)) from self._error
        return self._result


_STOP = object()
# dispatcher -> consumer "device freed" token (continuous mode): rides
# the request queue so the consumer has ONE blocking wait point
_FREE = object()


class MicroBatcher:
    """A bounded request queue behind one of two batching disciplines.

    ``run_batch(leaves, n_real, bucket) -> np.ndarray [bucket, A]`` is the
    execution backend (the server provides the AOT-compiled device call or
    the fallback tier); ``leaves`` are the bucket-stacked obs arrays.
    """

    def __init__(self, run_batch: Callable, template: ObsTemplate,
                 buckets: Sequence[int] = (1, 4, 8),
                 deadline_ms: float = 5.0, hub=None,
                 max_queue: int = 4096,
                 on_flush: Optional[Callable[[int, int], None]] = None,
                 tracer=None, mode: str = "deadline",
                 worker: Optional[str] = None,
                 version_provider: Optional[Callable[[], int]] = None):
        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be positive ints: {buckets!r}")
        if mode not in BATCH_MODES:
            raise ValueError(f"mode must be one of {BATCH_MODES}: {mode!r}")
        self.run_batch = run_batch
        self.template = template
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.deadline_s = float(deadline_ms) / 1e3
        self.hub = hub
        self.on_flush = on_flush
        self.mode = mode
        # fleet worker id: moves the queue-depth gauge to a worker-tagged
        # series and adds per-worker request/batch counters (None = the
        # historic single-server series, untouched)
        self.worker = worker
        self._wtag = {"worker": worker} if worker else {}
        # current-policy-version probe, read under flush_lock at each
        # dispatch so the stamped version IS the version the device call
        # used (None = unversioned backend)
        self.version_provider = version_provider
        # obs.slo.ServeTracer (or None): receives one compact record per
        # flush + rejection notes; all span math/emission happens on ITS
        # drainer thread, never here
        self.tracer = tracer
        self._next_trace_id = 0   # guarded-by: self._submit_lock
        # backpressure is enforced by the WAITING counter, not the queue
        # bound: continuous mode drains the queue into its pending list
        # continuously (the _FREE token must never be stuck behind a
        # backlog), so a bounded queue alone would never fill there —
        # max_queue would silently stop rejecting and queue_depth would
        # read ~0 under exactly the overload that routing/brownout key
        # on.  _waiting counts accepted requests not yet handed to a
        # device dispatch, wherever they sit (queue, pending list,
        # prepared slot); submit rejects when it reaches max_queue.
        self.max_queue = int(max_queue)
        self._waiting = 0   # guarded-by: self._submit_lock
        self._q: "queue.Queue" = queue.Queue()
        # continuous mode: depth-1 channel of PREPARED (stacked+padded)
        # batches between the forming consumer and the dispatcher thread —
        # one batch on the device, one formed and waiting, the rest queued
        self._slot: "queue.Queue" = queue.Queue(maxsize=1)
        # serializes every device dispatch against weight hot-swaps: the
        # VersionWatcher swaps params under this lock, so a swap lands
        # between device calls and no batch mixes policy versions
        self.flush_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False   # guarded-by: self._submit_lock
        # serializes submit's check+enqueue against stop's flag+sentinel:
        # an accepted request is therefore ALWAYS queued ahead of _STOP,
        # so it is served by the drain — without this, a submit that
        # passed the flag check could enqueue after the consumer exited
        # and its future would hang until the client timeout
        self._submit_lock = threading.Lock()

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            target = self._loop if self.mode == "deadline" \
                else self._loop_continuous
            self._thread = threading.Thread(target=target,
                                            name="gsc-serve-batcher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        """Drain-then-stop: requests queued before the stop are still
        answered; a ``submit`` racing it either lands ahead of the stop
        sentinel (and is served) or raises ServeError at the call site —
        never a silent until-timeout hang (the submit lock makes those
        the only two outcomes)."""
        if self._thread is None:
            return
        with self._submit_lock:
            self._stopping = True
            self._q.put(_STOP)
        self._thread.join(timeout)
        self._thread = None

    @property
    def queue_depth(self) -> int:
        """Accepted requests not yet handed to a device dispatch —
        honest in both modes (continuous mode's pending list is part of
        the backlog; the raw queue size is not the whole story there).

        Lock-free monitoring read: a torn int is impossible under the
        GIL and a one-update-stale depth is fine for gauges/routing —
        the R7 disables below and here are that documented tolerance."""
        return self._waiting  # gsc-lint: disable=R7 -- racy monitoring read, staleness tolerated

    # -------------------------------------------------------------- submit
    def submit(self, obs) -> ServeFuture:
        """Enqueue one request (any obs pytree matching the template).
        Template validation happens HERE, in the caller's thread — a
        malformed request raises at the call site and never reaches the
        shared device path.  A rejection (stopping / queue full) bumps
        ``serve_rejected_total{reason=..}`` BEFORE raising, so overload
        shows up in serve_stats and /metrics instead of vanishing into
        client-side exceptions."""
        leaves = self.template.flatten(obs)
        fut = ServeFuture()
        with self._submit_lock:
            if self._stopping:
                self._note_rejection("stopping", fut)
                raise ServeError("batcher is stopping — request rejected")
            if self._waiting >= self.max_queue:
                self._note_rejection("queue_full", fut)
                raise ServeError(
                    f"serve queue full ({self.max_queue} requests "
                    "waiting) — backpressure: retry or add capacity")
            fut.trace_id = self._next_trace_id
            self._next_trace_id += 1
            self._waiting += 1
            self._q.put((fut, leaves))
        # live depth between flushes: the flush-side sample alone reads
        # stale while requests pile up or the queue sits idle
        if self.hub is not None:
            self.hub.gauge("serve_queue_depth", self._waiting,  # gsc-lint: disable=R7 -- racy monitoring read, staleness tolerated
                           **self._wtag)
        return fut

    def _note_rejection(self, reason: str, fut: ServeFuture):
        if self.hub is not None:
            self.hub.counter("serve_rejected_total", reason=reason)
            if self.worker:
                self.hub.counter("serve_rejected_total", reason=reason,
                                 **self._wtag)
            self.hub.gauge("serve_queue_depth", self._waiting,  # gsc-lint: disable=R7 -- racy monitoring read, staleness tolerated
                           **self._wtag)
        if self.tracer is not None:
            self.tracer.note_rejection(reason, fut.wall_enqueued)

    # ------------------------------------------------------- deadline loop
    def _loop(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                break
            item[0].t_admitted = time.perf_counter()
            batch: List[Tuple[ServeFuture, List[np.ndarray]]] = [item]
            deadline = item[0].t_enqueued + self.deadline_s
            stop_after = False
            while len(batch) < self.buckets[-1]:
                remaining = deadline - time.perf_counter()
                try:
                    if remaining <= 0:
                        # deadline already spent (e.g. the previous device
                        # call outlasted it): still DRAIN what is already
                        # queued, non-blocking — otherwise overload
                        # degenerates to bucket-1 flushes exactly when
                        # batching matters most
                        nxt = self._q.get_nowait()
                    else:
                        nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                nxt[0].t_admitted = time.perf_counter()
                batch.append(nxt)
            self._flush(batch)
            if stop_after:
                break
        self._fail_leftovers()

    # ----------------------------------------------------- continuous loop
    def _loop_continuous(self):
        """Join-the-next-dispatch batching: this thread admits requests
        into a pending list continuously and SEALS a batch (stack + pad
        + hand to the dispatcher thread) the moment the device frees —
        so everything that arrived during the in-flight call becomes the
        next batch, and a lone request on an idle device dispatches
        immediately instead of waiting a deadline out.  A full bucket
        forming mid-flight seals early, so its host-side copies overlap
        the running device call.

        The seal-on-free discipline is what keeps continuous mode from
        degenerating: sealing eagerly whenever ANYTHING is pending would
        split staggered closed-loop arrivals into bucket-1 dispatches
        (measured: ~2.5x throughput loss) — batching must be paced by
        the device, not by the consumer thread's wake-up latency.

        The dispatcher signals completion by pushing a ``_FREE`` token
        through the request queue, giving this thread a single blocking
        wait point (new request | device freed | stop)."""
        dispatcher = threading.Thread(target=self._dispatch_loop,
                                      name="gsc-serve-dispatcher",
                                      daemon=True)
        dispatcher.start()
        pending: List[Tuple[ServeFuture, List[np.ndarray]]] = []
        device_free = True
        stopping = False
        while not (stopping and not pending and device_free):
            item = self._q.get()
            while True:
                if item is _STOP:
                    stopping = True
                elif item is _FREE:
                    device_free = True
                else:
                    item[0].t_admitted = time.perf_counter()
                    pending.append(item)
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
            # full batches formed while a call is in flight seal NOW:
            # their stack+pad copies overlap the running device call
            # (single producer, so the full() check cannot race)
            while len(pending) >= self.buckets[-1] \
                    and not self._slot.full():
                device_free = self._seal(pending)
            if pending and device_free:
                # the device just freed (or is idle): whatever arrived
                # joins this dispatch — never waits a deadline out
                device_free = self._seal(pending)
        self._slot.put(_STOP)
        dispatcher.join()
        self._fail_leftovers()

    def _seal(self, pending) -> bool:
        """Pop up to one largest-bucket's worth of pending requests,
        stack them, and hand the prepared batch to the dispatcher.
        Returns the new ``device_free`` state (always False): EVERY seal
        consumes the free token — leaving it True after an early seal
        would (a) let the next lone arrival seal into a degenerate
        bucket-1 dispatch behind the in-flight call, and (b) allow a
        second blocking ``_slot.put`` while the dispatcher can be
        blocked publishing ``_FREE`` into a full request queue — a
        mutual-block deadlock under exactly the overload the brownout
        tier is built for.  With the token consumed, a device_free seal
        only ever runs after a ``_FREE`` was received, i.e. when the
        dispatcher has already finished its queue put and is guaranteed
        to reach ``_slot.get`` — so this put can wait at most one
        slot-handoff, never forever."""
        batch = pending[:self.buckets[-1]]
        del pending[:self.buckets[-1]]
        k = len(batch)
        bucket = next(b for b in self.buckets if b >= k)
        stacked = self.template.stack_pad(
            [leaves for _, leaves in batch], bucket)
        self._slot.put((batch, stacked, k, bucket))
        return False

    def _dispatch_loop(self):
        while True:
            job = self._slot.get()
            if job is _STOP:
                return
            batch, stacked, k, bucket = job
            self._dispatch(batch, stacked, k, bucket)
            # wake the consumer: the device is free, seal the next batch
            # (rides the request queue so the consumer's single get()
            # sees it; the queue is effectively unbounded for the one
            # in-flight token)
            self._q.put(_FREE)

    def _fail_leftovers(self):
        # backstop: the submit lock means no future can land behind the
        # stop sentinel, but fail anything that somehow did (e.g. a second
        # _STOP from a double stop()) instead of hanging its client
        while True:
            try:
                leftover = self._q.get_nowait()
            except queue.Empty:
                return
            if leftover is _STOP or leftover is _FREE:
                continue
            fut, _ = leftover
            fut._error = ServeError("batcher stopped before this request "
                                    "was served")
            fut._event.set()

    # ------------------------------------------------------------ dispatch
    def _flush(self, batch):
        k = len(batch)
        bucket = next(b for b in self.buckets if b >= k)
        stacked = self.template.stack_pad([leaves for _, leaves in batch],
                                          bucket)
        self._dispatch(batch, stacked, k, bucket)

    def _dispatch(self, batch, stacked, k, bucket):
        # these k requests stop waiting now (dispatching, not backlog)
        with self._submit_lock:
            self._waiting -= k
        wall_dispatch = time.time()
        with self.flush_lock:
            # read the version INSIDE the lock: a hot-swap also runs
            # under flush_lock, so this is exactly the version the
            # device call below reads — the whole batch is answered by
            # one policy version, never a mix
            version = self.version_provider() \
                if self.version_provider is not None else None
            t0 = time.perf_counter()
            try:
                # R9 disabled below: holding flush_lock across the
                # device call IS the hot-swap contract — apply_weights
                # runs under the same lock, so a swap can never land
                # mid-flush and the version stamped above is exactly
                # the one the device computed with.  The cost (other
                # dispatchers stall one device round-trip) is the
                # design: one in-flight batch per worker.
                out = self.run_batch(stacked, k, bucket)  # gsc-lint: disable=R9 -- flush_lock-across-device-call is the hot-swap contract
                err = None
            except BaseException as e:  # noqa: BLE001 - replicated below
                err = e
            now = time.perf_counter()
        if err is not None:
            for fut, _ in batch:
                fut.policy_version = version
                fut._error = err
                # same stamp-before-set contract as the success path: a
                # done future never exposes t_completed=None, errored or
                # not (the tracer's failed-flush record still carries
                # None per request — there is no completion to decompose)
                fut.t_completed = time.perf_counter()
                fut._event.set()
            if self.hub is not None:
                self.hub.counter("serve_errors_total")
            if self.tracer is not None:
                # a failed device call must BURN the SLO budget, not
                # vanish from it: the engine counts these requests as
                # deadline misses / objective violations (they were
                # never answered), so attainment and the gated slo_*
                # metrics degrade with real serving failures
                self.tracer.record_flush({
                    "bucket": bucket, "n_real": k,
                    "wall_dispatch": wall_dispatch,
                    "t_dispatch": t0,
                    "t_device_done": now,
                    "queue_depth": self._waiting,  # gsc-lint: disable=R7 -- racy monitoring read, staleness tolerated
                    "policy_version": version,
                    "worker": self.worker,
                    "error": f"{type(err).__name__}: {err}",
                    "requests": [(fut.trace_id, fut.wall_enqueued,
                                  fut.t_enqueued, fut.t_admitted, None)
                                 for fut, _ in batch],
                })
            return
        out = np.asarray(out)
        for i, (fut, _) in enumerate(batch):
            fut._result = out[i]
            fut.policy_version = version
            if self.hub is not None:
                lat_ms = (now - fut.t_enqueued) * 1e3
                self.hub.observe("serve_latency_ms", lat_ms)
                self.hub.observe("serve_latency_ms", lat_ms,
                                 bucket=bucket)
            # completion stamp strictly BEFORE the event: a waiter that
            # observes done() (or the tracer record built below) must
            # never read t_completed=None off a finished future
            fut.t_completed = time.perf_counter()
            fut._event.set()
        if self.hub is not None:
            self.hub.counter("serve_requests_total", k)
            self.hub.counter("serve_batches_total", bucket=bucket)
            if self.worker:
                self.hub.counter("serve_requests_total", k, **self._wtag)
                self.hub.counter("serve_batches_total", **self._wtag)
            self.hub.observe("serve_batch_ms", (now - t0) * 1e3,
                             bucket=bucket)
            self.hub.gauge("serve_queue_depth", self._waiting,  # gsc-lint: disable=R7 -- racy monitoring read, staleness tolerated
                           **self._wtag)
        if self.tracer is not None:
            # deferred span emission: hand over the raw timestamps as one
            # record (plain floats, O(batch) appends) — the tracer's
            # drainer thread derives the queue/batch/device/fan-out
            # decomposition and emits the events off this thread.
            # `now` doubles as the device-done stamp, so the tracer's
            # reconstructed latency equals the serve_latency_ms values
            # recorded above exactly.
            self.tracer.record_flush({
                "bucket": bucket, "n_real": k,
                "wall_dispatch": wall_dispatch,
                "t_dispatch": t0, "t_device_done": now,
                "queue_depth": self._waiting,  # gsc-lint: disable=R7 -- racy monitoring read, staleness tolerated
                "policy_version": version,
                "worker": self.worker,
                "requests": [(fut.trace_id, fut.wall_enqueued,
                              fut.t_enqueued, fut.t_admitted,
                              fut.t_completed)
                             for fut, _ in batch],
            })
        if self.on_flush is not None:
            self.on_flush(k, bucket)
