"""Request micro-batcher: thread-safe queue + deadline-based flusher.

Concurrent coordination requests land on a bounded queue; one batcher
thread folds them into bucketed device batches:

- a flush fires when the OLDEST queued request has waited ``deadline_ms``
  or the largest bucket is full, whichever comes first — so a lone request
  pays at most the deadline, and a burst amortizes one device call;
- the flushed batch runs in the smallest configured bucket that fits it,
  padded by repeating the last real request (see
  ``ObsTemplate.stack_pad``); answers are sliced back per request.

Each request's answer is bit-identical regardless of batch-mates: the
bucketed policy is a ``vmap`` over the request axis, so rows never
interact (test-asserted padding-invariance).  Latency accounting flows
through the shared :class:`~gsc_tpu.obs.MetricsHub`:

- ``serve_latency_ms`` histogram (overall and tagged per bucket),
- ``serve_batch_ms`` device-call histogram per bucket,
- ``serve_requests_total`` / ``serve_batches_total{bucket=..}`` counters,
- ``serve_queue_depth`` gauge sampled at every flush.

The batcher is transport-agnostic: ``submit`` is the in-process API
(``PolicyServer`` wraps it); an RPC front-end would call the same method.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .policy import ObsTemplate


class ServeError(RuntimeError):
    """The device call answering this request failed (the error is
    replicated into every affected request's future)."""


class ServeFuture:
    """Minimal future for one request: blocks on ``result`` until the
    batcher fills it (or raises what the device call raised)."""

    __slots__ = ("_event", "_result", "_error", "t_enqueued")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self.t_enqueued = time.perf_counter()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still queued after "
                               f"{timeout}s")
        if self._error is not None:
            raise ServeError(str(self._error)) from self._error
        return self._result


_STOP = object()


class MicroBatcher:
    """One consumer thread over a bounded request queue.

    ``run_batch(leaves, n_real, bucket) -> np.ndarray [bucket, A]`` is the
    execution backend (the server provides the AOT-compiled device call or
    the fallback tier); ``leaves`` are the bucket-stacked obs arrays.
    """

    def __init__(self, run_batch: Callable, template: ObsTemplate,
                 buckets: Sequence[int] = (1, 4, 8),
                 deadline_ms: float = 5.0, hub=None,
                 max_queue: int = 4096,
                 on_flush: Optional[Callable[[int, int], None]] = None):
        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be positive ints: {buckets!r}")
        self.run_batch = run_batch
        self.template = template
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.deadline_s = float(deadline_ms) / 1e3
        self.hub = hub
        self.on_flush = on_flush
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        # serializes submit's check+enqueue against stop's flag+sentinel:
        # an accepted request is therefore ALWAYS queued ahead of _STOP,
        # so it is served by the drain — without this, a submit that
        # passed the flag check could enqueue after the consumer exited
        # and its future would hang until the client timeout
        self._submit_lock = threading.Lock()

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="gsc-serve-batcher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        """Drain-then-stop: requests queued before the stop are still
        answered; a ``submit`` racing it either lands ahead of the stop
        sentinel (and is served) or raises ServeError at the call site —
        never a silent until-timeout hang (the submit lock makes those
        the only two outcomes)."""
        if self._thread is None:
            return
        with self._submit_lock:
            self._stopping = True
            self._q.put(_STOP)
        self._thread.join(timeout)
        self._thread = None

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    # -------------------------------------------------------------- submit
    def submit(self, obs) -> ServeFuture:
        """Enqueue one request (any obs pytree matching the template).
        Template validation happens HERE, in the caller's thread — a
        malformed request raises at the call site and never reaches the
        shared device path."""
        leaves = self.template.flatten(obs)
        fut = ServeFuture()
        with self._submit_lock:
            if self._stopping:
                raise ServeError("batcher is stopping — request rejected")
            try:
                self._q.put_nowait((fut, leaves))
            except queue.Full:
                raise ServeError(
                    f"serve queue full ({self._q.maxsize} requests) — "
                    "backpressure: retry or add capacity")
        return fut

    # ---------------------------------------------------------------- loop
    def _loop(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                break
            batch: List[Tuple[ServeFuture, List[np.ndarray]]] = [item]
            deadline = item[0].t_enqueued + self.deadline_s
            stop_after = False
            while len(batch) < self.buckets[-1]:
                remaining = deadline - time.perf_counter()
                try:
                    if remaining <= 0:
                        # deadline already spent (e.g. the previous device
                        # call outlasted it): still DRAIN what is already
                        # queued, non-blocking — otherwise overload
                        # degenerates to bucket-1 flushes exactly when
                        # batching matters most
                        nxt = self._q.get_nowait()
                    else:
                        nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            self._flush(batch)
            if stop_after:
                break
        # backstop: the submit lock means no future can land behind the
        # stop sentinel, but fail anything that somehow did (e.g. a second
        # _STOP from a double stop()) instead of hanging its client
        while True:
            try:
                leftover = self._q.get_nowait()
            except queue.Empty:
                return
            if leftover is _STOP:
                continue
            fut, _ = leftover
            fut._error = ServeError("batcher stopped before this request "
                                    "was served")
            fut._event.set()

    def _flush(self, batch):
        k = len(batch)
        bucket = next(b for b in self.buckets if b >= k)
        stacked = self.template.stack_pad([leaves for _, leaves in batch],
                                          bucket)
        t0 = time.perf_counter()
        try:
            out = self.run_batch(stacked, k, bucket)
        except BaseException as e:  # noqa: BLE001 - replicate into futures
            for fut, _ in batch:
                fut._error = e
                fut._event.set()
            if self.hub is not None:
                self.hub.counter("serve_errors_total")
            return
        now = time.perf_counter()
        out = np.asarray(out)
        for i, (fut, _) in enumerate(batch):
            fut._result = out[i]
            if self.hub is not None:
                lat_ms = (now - fut.t_enqueued) * 1e3
                self.hub.observe("serve_latency_ms", lat_ms)
                self.hub.observe("serve_latency_ms", lat_ms,
                                 bucket=bucket)
            fut._event.set()
        if self.hub is not None:
            self.hub.counter("serve_requests_total", k)
            self.hub.counter("serve_batches_total", bucket=bucket)
            self.hub.observe("serve_batch_ms", (now - t0) * 1e3,
                             bucket=bucket)
            self.hub.gauge("serve_queue_depth", self._q.qsize())
        if self.on_flush is not None:
            self.on_flush(k, bucket)
