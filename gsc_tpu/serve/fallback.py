"""Non-learned serving tier: the SPR heuristic at schedule granularity.

When no checkpoint is given, the server answers from the shortest-path
heuristic instead of refusing — the serving analogue of ``cli simulate
--per-flow-algo spr``.  :class:`~gsc_tpu.sim.spr.ShortestPathAlgo` decides
per *flow* against live engine state; a serving request wants a *schedule*
tensor, so this module projects the same decision rule onto the schedule:

1. a source node with its own capacity keeps its traffic (SPR rule 1:
   process HERE);
2. otherwise all of its weight goes to the nearest capable node by
   shortest-path delay (rule 2), excluding unreachable nodes (the finite
   ``INF_DELAY`` sentinel, exactly as ``ShortestPathAlgo.decide``);
3. with no capable reachable node the weight stays put and the simulator
   records the authentic NODE_CAP drop (rule 3).

The projection is a pure function of the topology (capacities +
shortest-path delays), so the fallback tier computes ONE flat action at
server start and answers every request with it — microseconds per
request, no device involvement, same queue/latency accounting as the
learned tier.
"""
from __future__ import annotations

import numpy as np

from ..config.schema import EnvLimits
from ..topology.compiler import INF_DELAY, Topology


def spr_schedule_action(topo: Topology, limits: EnvLimits) -> np.ndarray:
    """Flat ``[A]`` scheduling action (rows already one-hot, so the env's
    threshold+renormalize post-processing is a fixed point)."""
    node_mask = np.asarray(topo.node_mask)
    cap = np.asarray(topo.node_cap)
    pd = np.asarray(topo.path_delay)
    n, c, s, _ = limits.scheduling_shape
    sched = np.zeros(limits.scheduling_shape, np.float32)
    capable = node_mask & (cap > 0)
    for src in range(n):
        if not node_mask[src]:
            continue
        if capable[src]:
            dst = src                                    # rule 1
        else:
            delays = np.where(capable, pd[src], INF_DELAY)
            dst = int(np.argmin(delays))                 # rule 2
            if delays[dst] >= INF_DELAY:
                dst = src                                # rule 3
        sched[src, :, :, dst] = 1.0
    return sched.reshape(-1)


class SPRFallbackPolicy:
    """Batcher backend for the fallback tier: replicates the precomputed
    SPR schedule per request (obs content is deliberately ignored — the
    heuristic is topology-static, which is exactly its value as the
    always-available bottom tier).

    ``sample_obs`` declares the request payload shape so clients stay
    tier-agnostic: the same obs pytree a learned-tier request carries is
    validated (and then ignored) here."""

    def __init__(self, topo: Topology, limits: EnvLimits, sample_obs):
        from .policy import ObsTemplate

        self.action = spr_schedule_action(topo, limits)
        self.template = ObsTemplate(sample_obs)

    def run_batch(self, leaves, n_real: int, bucket: int) -> np.ndarray:
        # tile, not broadcast_to: each request's future gets its own
        # WRITABLE row, matching the learned tier's contract (broadcast
        # views are read-only and alias one shared buffer)
        return np.tile(self.action[None, :], (bucket, 1))
