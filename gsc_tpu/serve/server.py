"""PolicyServer — AOT-compiled policy + artifact cache + micro-batcher.

Lifecycle of one serving process:

1. ``start()`` prepares every batch bucket BEFORE the first request:
   artifact-cache lookup -> ``jax.export.deserialize`` on a hit (no policy
   trace at all), else trace+lower via
   :meth:`~gsc_tpu.serve.policy.GreedyServePolicy.export_bucket` and
   persist the serialized module; either way the bucket is warmed with one
   dummy device call so the backend compile is also done up front.  A
   corrupt cache entry logs, recompiles and overwrites — it never fails a
   start.
2. ``submit(obs)`` enqueues a request on the micro-batcher and returns a
   :class:`~gsc_tpu.serve.batcher.ServeFuture`; ``submit_sync`` blocks.
3. ``close()`` drains the queue and emits the final ``serve_stats`` event.

Observability rides the run's :class:`~gsc_tpu.obs.MetricsHub`: the
batcher feeds the latency/queue series (see its module doc), the server
emits one ``serve_start`` event (tier, buckets, per-bucket cache hit +
prepare wall, total startup) and periodic + final ``serve_stats`` events
(requests, requests/s, p50/p99 overall and per bucket, occupancy,
rejections, and — with a tracer attached — the latency decomposition
per bucket plus the SLO snapshot) — ``tools/obs_report.py`` renders
them as the serving section.

Request-path tracing + SLO: pass a
:class:`~gsc_tpu.obs.slo.ServeTracer` (``tracer=``) to decompose every
request's latency into queue-wait / batch-wait / device / fan-out and
emit ``serve_flush`` + head-sampled ``serve_request_span`` events;
``slo=`` (an :class:`~gsc_tpu.obs.slo.SLOObjectives`) declares latency
objectives the engine tracks rolling attainment and error-budget burn
against, and ``slo_path=`` makes :meth:`close` write the final SLO
summary as ``slo.json``.  All three default off — the historic serve
path is byte-identical without them.

Without a checkpoint the server runs the SPR fallback tier
(:class:`~gsc_tpu.serve.fallback.SPRFallbackPolicy`) through the same
batcher and accounting, so the serving surface is always available.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Sequence

import numpy as np

from .batcher import MicroBatcher, ServeFuture
from .cache import ArtifactCache, cache_material
from .fallback import SPRFallbackPolicy
from .policy import (GreedyServePolicy, exec_fn_name, policy_fn_name,
                     shape_structs)

log = logging.getLogger("gsc_tpu.serve.server")


def _make_exec(exported, name: str):
    """Jit-wrap a deserialized exported module under a stable per-bucket
    name (compile telemetry + retrace assertions key on it).  The wrapper
    trace is trivial — the policy itself was traced at export time (or
    never, on a cache hit)."""
    import jax

    def _exec(params, *leaves):
        return exported.call(params, *leaves)

    _exec.__name__ = name
    return jax.jit(_exec)


class PolicyServer:
    """One serving process: compiled buckets (learned tier) or the SPR
    heuristic (fallback tier) behind a deadline micro-batcher."""

    def __init__(self, *, policy: Optional[GreedyServePolicy] = None,
                 params=None, fallback: Optional[SPRFallbackPolicy] = None,
                 buckets: Sequence[int] = (1, 4, 8),
                 deadline_ms: float = 5.0,
                 cache: Optional[ArtifactCache] = None,
                 fingerprint: str = "none",
                 precision: str = "f32", substep_impl: str = "xla",
                 graph_mode: bool = True,
                 hub=None, stats_interval: int = 50,
                 max_queue: int = 4096, perf=None,
                 tracer=None, slo=None, slo_path: Optional[str] = None,
                 mode: str = "deadline", worker: Optional[str] = None,
                 hot_swap_dir: Optional[str] = None,
                 swap_poll_s: float = 0.2):
        if (policy is None) == (fallback is None):
            raise ValueError("exactly one of policy (learned tier, with "
                             "params) or fallback (SPR tier) is required")
        if policy is not None and params is None:
            raise ValueError("the learned tier needs actor params")
        self.policy = policy
        self.params = params
        self.fallback = fallback
        self.tier = "learned" if policy is not None else "spr"
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.deadline_ms = float(deadline_ms)
        self.cache = cache
        self.fingerprint = fingerprint
        self.precision = precision
        self.substep_impl = substep_impl
        self.graph_mode = graph_mode
        self.hub = hub
        # device-cost ledger (obs.perf.CostLedger): with one, every bucket
        # records its serve_policy_b<B> compile cost at start() and the
        # measured latency histograms merge in at close() — perf.json
        # then carries per-bucket MFU next to the training entry points
        self.perf = perf
        # request-path tracing + SLO engine (obs.slo): the tracer turns
        # the batcher's timestamp records into span events and latency
        # decomposition on its own drainer thread; the engine (created
        # in start() when a tracer is attached) tracks deadline misses,
        # pad waste, arrival rate and — when `slo` declares objectives —
        # rolling attainment + error-budget burn.  slo_path: where
        # close() writes the final summary document (None = don't).
        self.tracer = tracer
        self.slo = slo
        self.slo_path = slo_path
        self.slo_engine = None
        self.stats_interval = max(int(stats_interval), 1)
        self.max_queue = max_queue
        # batching discipline (serve.batcher.BATCH_MODES): "deadline" is
        # the historic flush-cycle batcher, "continuous" forms the next
        # batch while the current device call is in flight
        self.mode = mode
        # fleet worker id: tags the queue-depth gauge + per-worker
        # counters and stamps serve_start/serve_stats/weight_swap events
        # (None = the historic single-server series, untouched)
        self.worker = worker
        self._wtag = {"worker": worker} if worker else {}
        # live weight hot-swap: watch this publish directory
        # (serve.fleet.WeightPublisher layout) and swap new versions in
        # between dispatches; policy_version stamps every flush
        self.hot_swap_dir = hot_swap_dir
        self.swap_poll_s = swap_poll_s
        self.watcher = None
        self.policy_version = 0
        self.swaps = 0
        self.batcher: Optional[MicroBatcher] = None
        self.startup: Dict = {}
        self._exec: Dict[int, object] = {}
        self._occupancy: Dict[int, int] = {}
        self._completed = 0
        self._last_stats_at = 0
        self._t_started = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "PolicyServer":
        t0 = time.perf_counter()
        per_bucket: Dict[str, Dict] = {}
        if self.tier == "learned":
            for b in self.buckets:
                per_bucket[str(b)] = self._prepare_bucket(b)
            run_batch = self._run_learned
            template = self.policy.template
        else:
            template = self.fallback.template
            run_batch = self.fallback.run_batch
        if self.tracer is not None:
            from ..obs.slo import SLOEngine
            self.slo_engine = SLOEngine(deadline_ms=self.deadline_ms,
                                        objectives=self.slo, hub=self.hub,
                                        tags=self._wtag)
            self.tracer.bind_engine(self.slo_engine)
            self.tracer.start()
        self.batcher = MicroBatcher(
            run_batch, template, buckets=self.buckets,
            deadline_ms=self.deadline_ms, hub=self.hub,
            max_queue=self.max_queue, on_flush=self._on_flush,
            tracer=self.tracer, mode=self.mode, worker=self.worker,
            version_provider=lambda: self.policy_version).start()
        if self.hub is not None and hasattr(self.hub, "live_gauge"):
            # the /metrics endpoint snapshots the hub on every scrape —
            # a live probe keeps serve_queue_depth current mid-run
            # instead of frozen at the last flush/submit sample (tagged
            # per worker in a fleet so N probes never collide)
            batcher = self.batcher
            self.hub.live_gauge("serve_queue_depth",
                                lambda: batcher.queue_depth, **self._wtag)
        if self.hot_swap_dir is not None:
            from .fleet import VersionWatcher
            self.watcher = VersionWatcher(self.hot_swap_dir, self,
                                          poll_s=self.swap_poll_s,
                                          hub=self.hub).start()
        self._t_started = time.perf_counter()
        self.startup = {
            "tier": self.tier,
            "startup_s": round(self._t_started - t0, 3),
            "buckets": per_bucket,
            "cache_dir": self.cache.root if self.cache else None,
        }
        if self.hub is not None:
            self.hub.event("serve_start", tier=self.tier,
                           buckets=list(self.buckets),
                           deadline_ms=self.deadline_ms,
                           mode=self.mode,
                           startup_s=self.startup["startup_s"],
                           bucket_prepare=per_bucket,
                           cache_dir=self.startup["cache_dir"],
                           fingerprint=self.fingerprint,
                           **({"worker": self.worker, "hot_swap_dir":
                               self.hot_swap_dir} if self.worker
                              or self.hot_swap_dir else {}))
        return self

    def _prepare_bucket(self, b: int) -> Dict:
        """Load-or-compile + warm one bucket; returns its prepare stats."""
        from jax import export as jax_export

        t0 = time.perf_counter()
        material = cache_material(
            fingerprint=self.fingerprint, template=self.policy.template,
            batch=b, precision=self.precision,
            substep_impl=self.substep_impl, graph_mode=self.graph_mode,
            # the actor is lowered through the configured GAT impl — a
            # module artifact compiled under one impl must miss under the
            # other (their numerics are only interpret-mode-equal)
            gnn_impl=self.policy.ddpg.actor.gnn_impl)
        exported, hit = None, False
        blob = self.cache.load(material) if self.cache else None
        if blob is not None:
            try:
                exported = jax_export.deserialize(bytearray(blob))
                hit = True
            except Exception as e:  # noqa: BLE001 - corrupt entry: recompile
                log.warning(
                    "serve artifact for bucket %d failed to deserialize "
                    "(%s: %s) — recompiling and overwriting the entry",
                    b, type(e).__name__, e)
        if exported is None:
            exported = self.policy.export_bucket(self.params, b)
            if self.cache is not None:
                self.cache.store(material, bytes(exported.serialize()))
        self._exec[b] = _make_exec(exported, exec_fn_name(b))
        self._warm_bucket(b)
        if self.perf is not None:
            # shapes-only AOT capture of the bucket's compiled policy —
            # FLOPs/bytes/fusions per batched call at startup, never
            # inside a request's latency (the warm call above already
            # paid the backend compile, so this lower mostly re-wraps it)
            self.perf.capture(
                policy_fn_name(b), self._exec[b],
                (shape_structs(self.params),
                 *self.policy.template.batch_structs(b)))
        return {"cache_hit": hit,
                "prepare_s": round(time.perf_counter() - t0, 3)}

    def _warm_bucket(self, b: int):
        """One dummy call so the backend compile (and the wrapper trace)
        happen at startup, never inside a request's latency."""
        import jax

        t = self.policy.template
        zeros = [np.zeros((b,) + s, d)
                 for s, d in zip(t.leaf_shapes, t.leaf_dtypes)]
        jax.block_until_ready(self._exec[b](self.params, *zeros))

    def close(self):
        if self.watcher is not None:
            # stop watching BEFORE the drain: a swap landing mid-teardown
            # has nothing left to serve anyway
            self.watcher.stop()
            self.watcher = None
        if self.batcher is not None:
            self.batcher.stop()
            self.batcher = None
        if self.hub is not None and hasattr(self.hub, "drop_live_gauge"):
            self.hub.drop_live_gauge("serve_queue_depth", **self._wtag)
            self.hub.gauge("serve_queue_depth", 0, **self._wtag)
        if self.tracer is not None:
            # final drain BEFORE the final stats event, so the last
            # flushes' spans and SLO updates are in the summary
            self.tracer.stop()
        self._emit_stats(final=True)
        if self.slo_engine is not None and self.slo_path is not None:
            from ..obs.slo import write_slo_json
            try:
                write_slo_json(self.slo_path, self._slo_doc())
            except OSError as e:   # a full disk must not mask teardown
                log.warning("slo.json not written to %s: %s",
                            self.slo_path, e)
        if self.perf is not None and self.hub is not None:
            # measured per-bucket FLUSH wall -> ledger timings: the
            # batcher's serve_batch_ms histogram wraps exactly one
            # device call per observation (run_batch in _flush), so
            # `dispatches` counts device calls — not requests — and
            # wall_s_mean is honest per-dispatch wall.  It still
            # includes host staging around the call, so the derived MFU
            # is a serving lower bound, not a kernel-only number.
            for b in self.buckets:
                s = self.hub.histogram_summary("serve_batch_ms", bucket=b)
                if s and s.get("count"):
                    self.perf.note_timing(policy_fn_name(b),
                                          s["sum"] / 1e3, int(s["count"]))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------ requests
    def submit(self, obs) -> ServeFuture:
        if self.batcher is None:
            raise RuntimeError("PolicyServer not started")
        return self.batcher.submit(obs)

    def submit_sync(self, obs, timeout: Optional[float] = 60.0):
        return self.submit(obs).result(timeout)

    @property
    def queue_depth(self) -> int:
        return self.batcher.queue_depth if self.batcher is not None else 0

    # ------------------------------------------------------------ hot-swap
    def apply_weights(self, leaves, version: int, fingerprint: str,
                      meta: Optional[Dict] = None):
        """Swap a published weight version in, strictly between device
        dispatches.

        Learned tier: ``leaves`` (host arrays in ``jax.tree_util``
        flatten order) must match the served params' leaf shapes/dtypes
        exactly — the AOT-compiled buckets were lowered for that
        signature, so a mismatch raises and the served weights stay
        untouched.  Device staging (``jnp.asarray``) happens BEFORE the
        flush lock is taken; the lock is held only for the reference
        swap, so a swap stalls serving by nanoseconds, not a transfer.

        SPR tier: the heuristic has no network weights — a published
        single-leaf artifact matching the precomputed action's
        shape/dtype swaps the action itself (recomputed topology), any
        other payload bumps the version stamp only.  Either way the full
        version/locking/event machinery runs, which is what a fallback-
        tier fleet exercises in CI.

        Zero requests are dropped or errored by a swap: the queue is
        never touched, and each dispatch stamps the version it actually
        ran under (the flush lock makes that exact)."""
        t0 = time.perf_counter()
        staged_params = staged_action = None
        if self.tier == "learned":
            import jax
            import jax.numpy as jnp

            cur_leaves, treedef = jax.tree_util.tree_flatten(self.params)
            if len(leaves) != len(cur_leaves):
                raise ValueError(
                    f"hot-swap version {version} has {len(leaves)} leaves, "
                    f"served params have {len(cur_leaves)}")
            for i, (new, cur) in enumerate(zip(leaves, cur_leaves)):
                new = np.asarray(new)
                if (tuple(new.shape) != tuple(jnp.shape(cur))
                        or str(new.dtype) != str(jnp.asarray(cur).dtype)):
                    raise ValueError(
                        f"hot-swap version {version} leaf {i} is "
                        f"{new.shape}/{new.dtype}, served params want "
                        f"{tuple(jnp.shape(cur))}/"
                        f"{jnp.asarray(cur).dtype} — the compiled "
                        "buckets cannot run it")
            staged_params = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(l) for l in leaves])
        else:
            action = self.fallback.action
            if len(leaves) == 1 and tuple(np.asarray(leaves[0]).shape) \
                    == tuple(action.shape):
                staged_action = np.asarray(leaves[0]).astype(action.dtype)
        lock = self.batcher.flush_lock if self.batcher is not None else None
        if lock is not None:
            lock.acquire()
        try:
            if staged_params is not None:
                self.params = staged_params
            if staged_action is not None:
                self.fallback.action = staged_action
            self.policy_version = int(version)
            self.fingerprint = fingerprint
        finally:
            if lock is not None:
                lock.release()
        self.swaps += 1
        swap_ms = (time.perf_counter() - t0) * 1e3
        if self.hub is not None:
            self.hub.counter("serve_weight_swaps_total", **self._wtag)
            self.hub.gauge("serve_policy_version", version, **self._wtag)
            self.hub.event(
                "weight_swap", version=int(version),
                fingerprint=fingerprint, tier=self.tier,
                swap_ms=round(swap_ms, 3),
                weights_applied=bool(staged_params is not None
                                     or staged_action is not None),
                requests_in_flight=self.queue_depth,
                **({"worker": self.worker} if self.worker else {}),
                **({"meta": meta} if meta else {}))

    # ------------------------------------------------------------ internals
    def _run_learned(self, leaves, n_real: int, bucket: int) -> np.ndarray:
        return np.asarray(self._exec[bucket](self.params, *leaves))

    def _on_flush(self, n_real: int, bucket: int):
        self._occupancy[bucket] = self._occupancy.get(bucket, 0) + n_real
        self._completed += n_real
        if self._completed - self._last_stats_at >= self.stats_interval:
            self._last_stats_at = self._completed
            self._emit_stats()

    def latency_summary(self, bucket: Optional[int] = None):
        if self.hub is None:
            return None
        tags = {"bucket": bucket} if bucket is not None else {}
        return self.hub.histogram_summary("serve_latency_ms", **tags)

    def _rejected_totals(self) -> Dict[str, int]:
        if self.hub is None:
            return {}
        return {reason: int(self.hub.get_counter("serve_rejected_total",
                                                 reason=reason))
                for reason in ("queue_full", "stopping")}

    def _decomposition(self) -> Dict[str, Dict[str, float]]:
        """Per-bucket latency-split means from the tracer's histograms:
        queue-wait, batch-formation wait, device wall (the historic
        serve_batch_ms), fan-out."""
        if self.hub is None:
            return {}
        out: Dict[str, Dict[str, float]] = {}
        for b in self.buckets:
            row = {}
            for metric, key in (("serve_queue_wait_ms", "queue_ms"),
                                ("serve_batch_wait_ms", "batch_ms"),
                                ("serve_batch_ms", "device_ms"),
                                ("serve_fanout_ms", "fanout_ms")):
                s = self.hub.histogram_summary(metric, bucket=b)
                if s and s.get("count"):
                    row[key] = round(s["mean"], 4)
            if row:
                out[str(b)] = row
        return out

    def slo_summary(self) -> Optional[Dict]:
        """Compact SLO verdict for the CLI's JSON output / serve_bench
        banking (the slo.json document is the full version)."""
        if self.slo_engine is None:
            return None
        snap = self.slo_engine.snapshot()
        out = {k: snap.get(k) for k in
               ("requests", "deadline_misses", "deadline_miss_ratio",
                "attainment", "burn_rate", "pad_waste",
                "queue_wait_frac", "arrival_rate_rps", "rejected")}
        out["p99_target_ms"] = (snap.get("objectives") or {}).get("p99_ms")
        return out

    def _slo_doc(self) -> Dict:
        """The full ``slo.json`` payload: engine snapshot + serving
        context + latency decomposition + overall percentiles."""
        from ..obs.slo import SLO_SCHEMA_VERSION

        lat = self.latency_summary() or {}
        doc = {
            "schema_version": SLO_SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "run": (self.hub.base_tags.get("run")
                    if self.hub is not None else None),
            "tier": self.tier,
            "buckets": list(self.buckets),
            "requests_completed": self._completed,
            "p50_latency_ms": round(lat.get("p50", 0.0), 4),
            "p99_latency_ms": round(lat.get("p99", 0.0), 4),
            "decomposition_ms": self._decomposition(),
            "spans_dropped": (self.tracer.spans_dropped
                              if self.tracer is not None else 0),
        }
        doc.update(self.slo_engine.snapshot())
        return doc

    def _emit_stats(self, final: bool = False):
        if self.hub is None:
            return
        elapsed = (time.perf_counter() - self._t_started) \
            if self._t_started else 0.0
        lat = self.latency_summary() or {}
        per_bucket = {}
        for b in self.buckets:
            s = self.latency_summary(b)
            if s:
                per_bucket[str(b)] = {"p50_ms": round(s["p50"], 3),
                                      "p99_ms": round(s["p99"], 3),
                                      "requests": int(s["count"])}
        extra = {}
        rejected = self._rejected_totals()
        # rejections always ride a traced run's stats (zeroes included —
        # "none rejected" is itself the signal); an untraced run only
        # reports them once one actually happened
        if self.tracer is not None or any(rejected.values()):
            extra["rejected"] = rejected
        if self.tracer is not None:
            # the tracer drains on its own cadence (<= its interval
            # stale here); the FINAL stats event runs after
            # tracer.stop()'s synchronous drain, so it is exact
            extra["decomposition"] = self._decomposition()
            if self.slo_engine is not None:
                snap = self.slo_engine.snapshot()
                extra["slo"] = {
                    k: snap.get(k) for k in
                    ("deadline_miss_ratio", "deadline_misses",
                     "attainment", "burn_rate", "arrival_rate_rps",
                     "pad_waste", "queue_wait_frac")}
                extra["slo"]["p99_target_ms"] = \
                    (snap.get("objectives") or {}).get("p99_ms")
        if self.worker:
            # fleet context: per-worker request/batch counters + the
            # worker's own completion count (the untagged histograms are
            # fleet aggregates, so `requests` below is fleet-wide)
            extra["worker"] = self.worker
            extra["worker_requests"] = self._completed
        if self.policy_version or self.swaps:
            extra["policy_version"] = self.policy_version
            extra["swaps"] = self.swaps
        self.hub.event(
            "serve_stats", tier=self.tier, final=final,
            requests=self._completed,
            rps=round(self._completed / elapsed, 3) if elapsed else 0.0,
            p50_ms=round(lat.get("p50", 0.0), 3),
            p99_ms=round(lat.get("p99", 0.0), 3),
            mean_ms=round(lat.get("mean", 0.0), 3),
            max_ms=round(lat.get("max", 0.0), 3),
            queue_depth=int(self.hub.get_gauge("serve_queue_depth",
                                               **self._wtag) or 0),
            occupancy={str(b): n for b, n in
                       sorted(self._occupancy.items())},
            buckets=per_bucket, **extra)
