"""Serving fleet: train-while-serve weight hot-swap + multi-worker dispatch.

ROADMAP item 3's fleet, in three pieces:

- :class:`WeightPublisher` — the TRAINING side.  Writes versioned,
  fingerprint-keyed weight artifacts into a publish directory: one
  ``v<NNNNN>.npz`` blob (the flattened param leaves) + one
  ``v<NNNNN>.json`` manifest (version, content fingerprint, leaf
  shapes/dtypes, caller metadata) per publish, and an atomically-rewritten
  ``latest.json`` pointer.  Every write is temp-file + ``os.replace`` so a
  killed trainer can never leave a torn blob behind a validating pointer.
  Old versions are pruned (``keep_versions``), and when the publisher is
  handed the serving tier's :class:`~gsc_tpu.serve.cache.ArtifactCache` it
  also GCs stale compiled-policy entries (``ArtifactCache.prune``) — the
  per-version artifact sets hot-swap publishing creates would otherwise
  grow without bound.

- :class:`VersionWatcher` — the WORKER side.  A daemon thread polls
  ``latest.json``; when a newer version appears it loads + fingerprint-
  validates the blob, stages the leaves onto the device, and calls
  ``PolicyServer.apply_weights`` — which swaps the served params under the
  batcher's ``flush_lock``, strictly BETWEEN device dispatches.  The swap
  contract: no batch ever mixes policy versions (the version stamped on a
  flush is read under the same lock the swap takes), zero requests are
  dropped or errored across a swap (the queue is untouched; in-flight
  futures complete under the version that dispatched them), and a corrupt
  or mismatched artifact is skipped loudly (counter + log) without
  touching the served weights.

- :class:`FleetDispatcher` — N :class:`~gsc_tpu.serve.server.PolicyServer`
  workers behind least-queue-depth routing (Podracer-style per-device
  actors, arXiv 2104.06272), with SLO-burn-driven brownout: when the
  fleet's error budget burns faster than ``brownout_burn`` and the least
  loaded worker already has a backlog — or a worker rejects on a full
  queue — overflow is shed to the SPR fallback tier (TF-Agents'
  batched-everything bottom tier, arXiv 1709.02878) instead of being
  rejected.  Every shed request is counted
  (``serve_brownout_total{reason=slo_burn|overflow}``).

The publisher/watcher protocol is plain files on purpose: the trainer and
the serving fleet share nothing but a directory (local disk, NFS, a
GCS-fuse mount), which is exactly the Podracer learner→actor weight path
minus the RPC dependency.  One writer per directory; any number of
watchers.
"""
from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import re
import tempfile
import threading
import time
import zipfile
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batcher import ServeError, ServeFuture

log = logging.getLogger("gsc_tpu.serve.fleet")

# weight-artifact layout version (bump on any blob/manifest change)
WEIGHTS_FORMAT = 1

_VERSION_RE = re.compile(r"^v(\d{5,})\.json$")


def _vname(version: int) -> str:
    return f"v{version:05d}"


def params_fingerprint(leaves: Sequence[np.ndarray]) -> str:
    """Content identity of a flattened param tree: sha256 over every
    leaf's shape, dtype and bytes in leaf order — the weight-artifact
    analogue of ``utils.checkpoint.checkpoint_fingerprint`` (retraining
    changes it, a republish of identical weights does not)."""
    h = hashlib.sha256()
    for leaf in leaves:
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _leaf_sig(leaves: Sequence[np.ndarray]) -> List[List]:
    return [[list(np.asarray(l).shape), str(np.asarray(l).dtype)]
            for l in leaves]


class WeightPublisher:
    """Training-side writer of versioned weight artifacts.

    ``publish(params)`` accepts any pytree (or an already-flat leaf
    list); the leaves are flattened in ``jax.tree_util`` order, which is
    the order the watcher rebuilds them in — publisher and worker must
    agree on the tree structure (they do: both sides hold the same actor
    params template).

    Two delivery channels, independently optional:

    - ``root`` — the on-disk fleet protocol above, byte-identical
      whether or not subscribers are also attached;
    - ``subscribers`` — same-process callables ``fn(record, params)``
      invoked after each publish with the ORIGINAL params pytree
      (zero-copy: device arrays pass by reference, no npz, no
      fingerprint sync).  ``root=None`` makes the publisher purely
      in-process — the async actor/learner bus — where the record
      carries ``fingerprint=None`` (hashing would force a device->host
      sync per publish for a consumer that never validates bytes; the
      in-process handoff cannot tear).

    One-gather contract (``--async --mesh``): a learner on a sharded
    mesh gathers params to host numpy ONCE per publish (run_async's
    ``maybe_publish``) and hands the host tree here — ``_flatten``'s
    ``np.asarray`` is then a zero-copy view, so the npz the serving
    fleet's watchers read from disk and the leaves the in-process actor
    subscribers adopt are the SAME host bytes.  ``_flatten`` still
    accepts device/sharded leaves from other callers (``device_get``
    assembles them), so sync-path publishes are unchanged."""

    def __init__(self, root: Optional[str] = None, keep_versions: int = 8,
                 hub=None, artifact_cache=None, artifact_keep: int = 8,
                 subscribers: Sequence[Callable] = (), fault_plan=None):
        if keep_versions < 1:
            raise ValueError(f"keep_versions must be >= 1: {keep_versions}")
        self.root = None if root is None else os.path.abspath(root)
        if self.root is not None:
            os.makedirs(self.root, exist_ok=True)
        self.keep_versions = int(keep_versions)
        self.hub = hub
        # chaos hook: the publish_corrupt site fires here, keyed by the
        # published version (None outside injected runs)
        self.fault_plan = fault_plan
        # the serving tier's compiled-policy cache (optional): pruned
        # after each publish so per-fingerprint artifact sets don't
        # accumulate one generation per published version
        self.artifact_cache = artifact_cache
        self.artifact_keep = int(artifact_keep)
        self.subscribers: List[Callable] = list(subscribers)
        self._version = (self._scan_latest_version()
                         if self.root is not None else 0)

    def subscribe(self, fn: Callable) -> Callable:
        """Attach an in-process ``fn(record, params)`` delivery target;
        returns ``fn`` so watchers can hold it for unsubscribe."""
        self.subscribers.append(fn)
        return fn

    def unsubscribe(self, fn: Callable):
        try:
            self.subscribers.remove(fn)
        except ValueError:
            pass

    def _scan_latest_version(self) -> int:
        latest = 0
        for path in glob.glob(os.path.join(self.root, "v*.json")):
            m = _VERSION_RE.match(os.path.basename(path))
            if m:
                latest = max(latest, int(m.group(1)))
        return latest

    @property
    def version(self) -> int:
        """The last published version (0 = nothing published yet)."""
        return self._version

    @staticmethod
    def _params_finite(params) -> bool:
        """Host-side finite scan over every inexact leaf (one host read
        per leaf — publish cadence, never a dispatch path)."""
        import jax
        for leaf in jax.tree_util.tree_flatten(params)[0]:
            arr = np.asarray(jax.device_get(leaf))
            if np.issubdtype(arr.dtype, np.floating) \
                    and not np.isfinite(arr).all():
                return False
        return True

    def publish(self, params, meta: Optional[Dict] = None,
                verified: bool = False) -> Optional[Dict]:
        """Write the next version; returns the manifest record, or None
        when the finite gate refuses the params.

        BOTH delivery channels are finite-gated: a non-finite tree never
        bumps the version, never writes an artifact and never reaches a
        subscriber — a poisoned learner state cannot fan out to actors
        or the hot-swap fleet through either path.  Callers that already
        proved the leaves finite (run_async's ``maybe_publish``, the
        trainer's pre-publish gates) pass ``verified=True`` to skip the
        redundant host scan."""
        if not verified and not self._params_finite(params):
            log.warning("publish refused: non-finite leaves at version "
                        "%d — a poisoned version must never reach a "
                        "watcher", self._version + 1)
            if self.hub is not None:
                self.hub.counter("weight_publish_skipped_total")
                self.hub.event("weight_publish_skipped",
                               version=self._version + 1,
                               reason="non_finite")
            return None
        version = self._version + 1
        name = _vname(version)
        # injected in-flight corruption, keyed by the published version:
        # the artifact/leaves are corrupted AFTER the gate above, so the
        # watchers' validation (fingerprint on the file path, the finite
        # gate on the in-process path) is what must catch it
        corrupt = (self.fault_plan.fire("publish_corrupt", version)
                   if self.fault_plan is not None else None)
        if self.root is None:
            record = {
                "format": WEIGHTS_FORMAT,
                "version": version,
                "fingerprint": None,
                "blob": None,
                "leaves": None,
                "ts": round(time.time(), 3),
                "meta": meta or {},
            }
        else:
            leaves = self._flatten(params)
            fingerprint = params_fingerprint(leaves)
            blob_path = os.path.join(self.root, name + ".npz")
            # atomic blob: npz to a temp file, then rename into place
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".npz.tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez(f, **{f"leaf_{i}": np.asarray(l)
                                   for i, l in enumerate(leaves)})
                os.replace(tmp, blob_path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            if corrupt is not None:
                # flip one byte mid-blob: the manifest keeps the CLEAN
                # fingerprint, so load_version's content check fails and
                # every watcher parks this version
                with open(blob_path, "r+b") as f:
                    f.seek(os.path.getsize(blob_path) // 2)
                    b = f.read(1) or b"\x00"
                    f.seek(-len(b), os.SEEK_CUR)
                    f.write(bytes([b[0] ^ 0xFF]))
            record = {
                "format": WEIGHTS_FORMAT,
                "version": version,
                "fingerprint": fingerprint,
                "blob": os.path.basename(blob_path),
                "leaves": _leaf_sig(leaves),
                "ts": round(time.time(), 3),
                "meta": meta or {},
            }
            from ..obs.sinks import write_atomic_json
            write_atomic_json(os.path.join(self.root, name + ".json"),
                              record)
            # the pointer goes last: a watcher that reads it can always
            # trust the blob+manifest it names are complete
            write_atomic_json(os.path.join(self.root, "latest.json"),
                              record)
        self._version = version
        if self.root is not None:
            self._prune_versions()
        if self.artifact_cache is not None:
            try:
                self.artifact_cache.prune(keep_latest=self.artifact_keep)
            except OSError as e:   # GC must never fail a publish
                log.warning("artifact-cache prune failed: %s", e)
        if self.hub is not None:
            self.hub.event("weight_publish", version=version,
                           fingerprint=record["fingerprint"],
                           **({"meta": meta} if meta else {}))
            self.hub.gauge("serve_published_version", version)
        deliver = params
        if corrupt is not None:
            # in-process corruption: subscribers receive NaN leaves —
            # the VersionWatcher's finite gate must park the version
            from ..resilience.guard import poison_tree
            deliver = poison_tree(params)
        for sub in list(self.subscribers):
            try:   # a broken subscriber must not fail the fleet publish
                sub(record, deliver)
            except Exception:
                log.exception("publish subscriber failed at version %d",
                              version)
        return record

    @staticmethod
    def _flatten(params) -> List[np.ndarray]:
        if isinstance(params, (list, tuple)) and all(
                isinstance(l, np.ndarray) for l in params):
            return list(params)
        import jax
        leaves = jax.tree_util.tree_flatten(params)[0]
        # device_get assembles sharded leaves (a multi-device mesh leaf
        # cannot np.asarray directly on every jax version); host numpy
        # passes through untouched, so a pre-gathered tree stays
        # zero-copy
        return [np.asarray(jax.device_get(l)) for l in leaves]

    def _prune_versions(self):
        """Keep the newest ``keep_versions`` (the latest is never
        touched); a blob whose manifest is already gone — or vice versa
        (a crashed earlier prune) — still gets collected."""
        versions = sorted(
            {int(m.group(1))
             for p in glob.glob(os.path.join(self.root, "v*.json"))
             for m in [_VERSION_RE.match(os.path.basename(p))] if m}
            | {int(m.group(1))
               for p in glob.glob(os.path.join(self.root, "v*.npz"))
               for m in [re.match(r"^v(\d{5,})\.npz$",
                                  os.path.basename(p))] if m},
            reverse=True)
        for version in versions[self.keep_versions:]:
            if version == self._version:
                continue
            for suffix in (".json", ".npz"):   # manifest first: a
                # pointer-less blob is untrusted, the reverse is a
                # manifest naming a missing blob (load_version rejects
                # both, but manifest-first never exposes the second)
                try:
                    os.unlink(os.path.join(self.root,
                                           _vname(version) + suffix))
                except OSError:
                    pass


def read_latest(root: str) -> Optional[Dict]:
    """The current ``latest.json`` record; None when missing, torn or not
    describing a weights artifact (all tolerated — the watcher just polls
    again)."""
    try:
        with open(os.path.join(root, "latest.json")) as f:
            rec = json.load(f)
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    if not isinstance(rec, dict) or rec.get("format") != WEIGHTS_FORMAT \
            or not isinstance(rec.get("version"), int):
        return None
    return rec


def load_version(root: str, record: Dict) -> List[np.ndarray]:
    """Load + validate one published version's leaves.  Raises
    ``ValueError`` when the blob is missing/corrupt, the leaf signature
    disagrees with the manifest, or the content fingerprint does not
    match — a watcher must never swap unverified bytes in."""
    blob_path = os.path.join(root, record["blob"])
    try:
        with np.load(blob_path) as z:
            leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
        # BadZipFile: npz member reads are CRC-checked, so a torn or
        # bit-flipped blob can surface here before the fingerprint pass
        raise ValueError(f"weights blob unreadable: {blob_path} "
                         f"({type(e).__name__}: {e})")
    if _leaf_sig(leaves) != record.get("leaves"):
        raise ValueError(f"weights blob leaf signature does not match its "
                         f"manifest: {blob_path}")
    fp = params_fingerprint(leaves)
    if fp != record.get("fingerprint"):
        raise ValueError(f"weights blob fingerprint mismatch: {blob_path} "
                         f"(manifest {record.get('fingerprint')!r:.20} != "
                         f"content {fp!r:.20})")
    return leaves


class VersionWatcher:
    """Worker-side poller: swaps newly published versions into a running
    :class:`~gsc_tpu.serve.server.PolicyServer` between dispatches.

    Two sources, mirroring the publisher's two channels:

    - ``root`` — the on-disk protocol (poll ``latest.json``, load +
      fingerprint-validate the blob);
    - ``publisher`` — an in-process :class:`WeightPublisher` this
      watcher subscribes to: each publish lands ``(record, params)`` in
      a latest-wins inbox (delivery runs in the PUBLISHER's thread and
      only stores a reference), and ``poll_once`` — still called by the
      consumer's own thread, between its dispatches — adopts from the
      inbox with no filesystem, no npz and no host copy.  The apply path
      and swap discipline are identical either way."""

    def __init__(self, root: Optional[str], server, poll_s: float = 0.2,
                 hub=None, max_retries: int = 5, publisher=None):
        if root is None and publisher is None:
            raise ValueError("VersionWatcher needs a root directory or an "
                             "in-process publisher")
        self.root = None if root is None else os.path.abspath(root)
        self.publisher = publisher
        self._inbox: Optional[Tuple[Dict, object]] = None   # guarded-by: self._inbox_lock
        self._inbox_lock = threading.Lock()
        if publisher is not None:
            self._subscription = publisher.subscribe(self._on_publish)
        self.server = server
        self.poll_s = float(poll_s)
        self.hub = hub
        # bounded per-version retry budget: a transient read failure
        # (NFS/GCS-fuse close-to-open lag can expose the manifest before
        # the blob settles) must not strand a worker on the old version
        # forever — but a genuinely corrupt artifact must not be
        # re-logged every poll either.  After max_retries the version is
        # parked until a strictly newer one appears.
        self.max_retries = int(max_retries)
        self.swaps = 0
        self._failed_version: Optional[int] = None
        self._failed_tries = 0
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "VersionWatcher":
        if self._thread is None:
            self._stop_event.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="gsc-serve-watcher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop_event.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)
        if self.publisher is not None:
            self.publisher.unsubscribe(self._subscription)

    def _run(self):
        while not self._stop_event.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:   # a poll crash must not kill the thread
                log.exception("version watcher poll failed")

    def _on_publish(self, record: Dict, params):
        """In-process delivery (runs in the publisher's thread): store a
        reference, latest wins — adoption stays with poll_once in the
        consumer's own thread."""
        with self._inbox_lock:
            self._inbox = (record, params)

    def poll_once(self) -> bool:
        """One poll; returns True iff a swap happened."""
        if self.publisher is not None:
            with self._inbox_lock:
                item, self._inbox = self._inbox, None
            if item is None:
                return False
            rec, params = item
        else:
            rec = read_latest(self.root)
        if rec is None or rec["version"] <= self.server.policy_version:
            return False
        if rec["version"] == self._failed_version \
                and self._failed_tries >= self.max_retries:
            return False   # parked: retried enough, wait for a newer one
        try:
            if self.publisher is not None:
                import jax
                leaves = jax.tree_util.tree_leaves(params)
                # the in-process analogue of the file path's fingerprint
                # validation: a published version with non-finite leaves
                # must never be adopted.  ValueError routes through the
                # same parked-retry bookkeeping below (one host read per
                # leaf, publish cadence only).
                for i, leaf in enumerate(leaves):
                    arr = np.asarray(jax.device_get(leaf))
                    if np.issubdtype(arr.dtype, np.floating) \
                            and not np.isfinite(arr).all():
                        raise ValueError(
                            f"non-finite leaf {i} in in-process "
                            f"published version {rec['version']} — "
                            f"refusing to adopt")
            else:
                leaves = load_version(self.root, rec)
            self.server.apply_weights(leaves, rec["version"],
                                      rec["fingerprint"],
                                      meta=rec.get("meta"))
        except (ValueError, OSError) as e:
            if rec["version"] == self._failed_version:
                self._failed_tries += 1
            else:
                self._failed_version = rec["version"]
                self._failed_tries = 1
            log.warning(
                "hot-swap to version %s skipped (attempt %d/%d): %s",
                rec.get("version"), self._failed_tries,
                self.max_retries, e)
            if self.hub is not None:
                self.hub.counter("serve_swap_failed_total")
            return False
        self._failed_version = None
        self._failed_tries = 0
        self.swaps += 1
        return True


class FleetDispatcher:
    """Least-queue-depth routing over N workers + SLO-burn brownout.

    ``workers`` are started/closed by the dispatcher (so are the
    brownout tier and each worker's :class:`VersionWatcher` — the
    server owns its watcher).  ``spr`` is the optional brownout target:
    a fallback-tier :class:`PolicyServer` that absorbs overflow instead
    of the fleet rejecting it."""

    def __init__(self, workers: Sequence, spr=None, hub=None,
                 brownout_burn: Optional[float] = 2.0,
                 burn_refresh_s: float = 0.25):
        if not workers:
            raise ValueError("a fleet needs at least one worker")
        self.workers = list(workers)
        self.spr = spr
        self.hub = hub
        # error-budget burn rate above which (with a backlog on the least
        # loaded worker) new load sheds to the SPR tier; None disables
        # proactive shedding (overflow shedding on queue_full stays on)
        self.brownout_burn = brownout_burn
        self.burn_refresh_s = float(burn_refresh_s)
        self._burn_cache: Tuple[float, Optional[float]] = (0.0, None)   # guarded-by: self._burn_lock
        self._burn_lock = threading.Lock()
        self._series_ts = 0.0   # last flight-recorder sample (monotonic)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "FleetDispatcher":
        for w in self.workers:
            w.start()
        if self.spr is not None:
            self.spr.start()
        if self.hub is not None:
            self.hub.event(
                "fleet_start", workers=[w.worker for w in self.workers],
                tier=self.workers[0].tier,
                brownout=("spr" if self.spr is not None else None),
                brownout_burn=self.brownout_burn)
        return self

    def close(self):
        # one forced ring sample on the way out, so even a short load
        # leaves the fleet's final queue/occupancy picture in history
        self.sample_series(force=True)
        for w in self.workers:
            w.close()
        if self.spr is not None:
            self.spr.close()
        if self.hub is not None:
            # fleet-level final record AFTER the workers' final
            # serve_stats: the per-worker events carry worker-local
            # counts, this one carries the fleet totals obs_report's
            # fleet view leads with
            self.hub.event(
                "fleet_stats", final=True,
                workers=[w.worker for w in self.workers],
                requests=self.completed, swaps=self.swap_total(),
                brownout={reason: int(self.hub.get_counter(
                    "serve_brownout_total", reason=reason))
                    for reason in ("slo_burn", "overflow")},
                per_worker={w.worker: {
                    "requests": w._completed,
                    "policy_version": w.policy_version,
                    "swaps": w.swaps,
                    "occupancy": {str(b): n for b, n in
                                  sorted(w._occupancy.items())},
                } for w in self.workers},
                slo=self.slo_summary())

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------ requests
    def submit(self, obs) -> ServeFuture:
        """Route one request: least queue depth wins; ties go to the
        first worker (stable under the idle fleet).  Sheds to the SPR
        tier on sustained budget burn (proactive) or a full worker queue
        (reactive) — the fleet only rejects when there is nowhere left
        to put the request."""
        self.sample_series()
        worker = min(self.workers, key=lambda w: w.queue_depth)
        if self.spr is not None and self._should_brownout(worker):
            self._count_brownout("slo_burn")
            return self.spr.submit(obs)
        try:
            return worker.submit(obs)
        except ServeError:
            if self.spr is None:
                raise
            self._count_brownout("overflow")
            return self.spr.submit(obs)

    def submit_sync(self, obs, timeout: Optional[float] = 60.0):
        return self.submit(obs).result(timeout)

    # ------------------------------------------------------------ brownout
    def _count_brownout(self, reason: str):
        if self.hub is not None:
            self.hub.counter("serve_brownout_total", reason=reason)

    def _should_brownout(self, worker) -> bool:
        if self.brownout_burn is None or worker.queue_depth < 1:
            return False
        burn = self._fleet_burn()
        return burn is not None and burn > self.brownout_burn

    def _fleet_burn(self) -> Optional[float]:
        """Max error-budget burn rate across the workers' SLO engines,
        refreshed at ``burn_refresh_s`` cadence (an engine snapshot walks
        its rolling window — too heavy per submit).  Each refresh also
        feeds the flight-recorder rings (:meth:`sample_series`) — the
        serving fleet's history rides the existing rate limit, never a
        per-submit cost."""
        now = time.monotonic()
        with self._burn_lock:
            ts, burn = self._burn_cache
            if now - ts < self.burn_refresh_s:
                return burn
            burns = []
            for w in self.workers:
                engine = getattr(w, "slo_engine", None)
                if engine is None:
                    continue
                snap = engine.snapshot()
                b = snap.get("burn_rate")
                if b is not None:
                    burns.append(b)
                self._series_from_snapshot(w, snap)
            burn = max(burns) if burns else None
            self._burn_cache = (now, burn)
            if burn is not None and self.hub is not None:
                self.hub.series("serve_burn_rate", burn)
            return burn

    def _series_from_snapshot(self, worker, snap: Dict):
        """One worker's ring points from an engine snapshot it already
        paid for: pad waste per bucket + overall (``series`` no-ops on a
        history-free hub, so this is free when the recorder is off)."""
        if self.hub is None:
            return
        pad = snap.get("pad_waste")
        if pad is not None:
            self.hub.series("serve_pad_waste", pad, worker=worker.worker)
        for bucket, rec in (snap.get("per_bucket") or {}).items():
            bpad = rec.get("pad_waste")
            if bpad is not None:
                self.hub.series("serve_pad_waste", bpad,
                                worker=worker.worker, bucket=bucket)

    def sample_series(self, force: bool = False):
        """Feed the flight-recorder rings one fleet sample: per-worker
        queue depth and per-bucket batch occupancy, plus the SLO-derived
        points (pad waste, burn) via :meth:`_fleet_burn`.  Called from
        :meth:`submit` but self-rate-limited to ``burn_refresh_s`` — the
        dispatch path only ever pays an attribute check and a clock
        read.  No-op without a hub or without a series window."""
        if self.hub is None \
                or getattr(self.hub, "series_store", None) is None:
            return
        now = time.monotonic()
        if not force and now - self._series_ts < self.burn_refresh_s:
            return
        self._series_ts = now
        for w in self.workers:
            self.hub.series("serve_queue_depth", w.queue_depth,
                            worker=w.worker)
            for bucket, n in sorted(w._occupancy.items()):
                self.hub.series("serve_occupancy", n, worker=w.worker,
                                bucket=bucket)
        self._fleet_burn()

    # --------------------------------------------------------------- stats
    @property
    def completed(self) -> int:
        total = sum(w._completed for w in self.workers)
        if self.spr is not None:
            total += self.spr._completed
        return total

    def swap_total(self) -> int:
        return sum(w.swaps for w in self.workers)

    def slo_summary(self) -> Optional[Dict]:
        doc = self.merged_slo()
        if doc is None:
            return None
        out = {k: doc.get(k) for k in
               ("requests", "deadline_misses", "deadline_miss_ratio",
                "attainment", "burn_rate", "pad_waste",
                "queue_wait_frac", "arrival_rate_rps", "rejected")}
        out["p99_target_ms"] = (doc.get("objectives") or {}).get("p99_ms")
        return out

    def merged_slo(self) -> Optional[Dict]:
        """One fleet-level SLO document from the workers' engines.

        Counts (requests, misses, flushes, rejections) sum exactly;
        window-derived ratios merge as weighted means (attainment by
        window size, pad waste by flushes, queue-wait fraction by
        requests) — a faithful approximation, since the per-worker sums
        behind them are not exposed.  Burn is recomputed from the merged
        attainment so the fleet number stays internally consistent."""
        snaps = [(w, w.slo_engine.snapshot()) for w in self.workers
                 if getattr(w, "slo_engine", None) is not None]
        if not snaps:
            return None
        first = snaps[0][1]
        requests = sum(s["requests"] for _, s in snaps)
        misses = sum(s["deadline_misses"] for _, s in snaps)
        errored = sum(s["errored_requests"] for _, s in snaps)
        flushes = sum(s["flushes"] for _, s in snaps)
        rejected: Dict[str, int] = {}
        for _, s in snaps:
            for reason, n in (s.get("rejected") or {}).items():
                rejected[reason] = rejected.get(reason, 0) + int(n)

        def wmean(key, weight_key):
            num = den = 0.0
            for _, s in snaps:
                v, w = s.get(key), s.get(weight_key)
                if key == "attainment":
                    w = (s.get("window") or {}).get("size")
                if v is None or not w:
                    continue
                num += v * w
                den += w
            return round(num / den, 6) if den else None

        attainment = wmean("attainment", "window")
        burn = None
        if attainment is not None:
            budget = 1.0 - first["objectives"]["target_attainment"]
            burn = round((1.0 - attainment) / budget, 4)
        rates = [s.get("arrival_rate_rps") for _, s in snaps
                 if s.get("arrival_rate_rps") is not None]
        return {
            "fleet_workers": [w.worker for w, _ in snaps],
            "deadline_ms": first["deadline_ms"],
            "objectives": first["objectives"],
            "requests": requests,
            "errored_requests": errored,
            "deadline_misses": misses,
            "deadline_miss_ratio": (round(misses / requests, 6)
                                    if requests else None),
            "attainment": attainment,
            "burn_rate": burn,
            "arrival_rate_rps": (round(sum(rates), 3) if rates else None),
            "flushes": flushes,
            "pad_waste": wmean("pad_waste", "flushes"),
            "queue_wait_frac": wmean("queue_wait_frac", "requests"),
            "rejected": rejected,
            "per_worker": {w.worker: {
                "requests": s["requests"],
                "deadline_miss_ratio": s["deadline_miss_ratio"],
                "attainment": s["attainment"],
                "pad_waste": s["pad_waste"],
            } for w, s in snaps},
        }
