"""AOT-compilable greedy-policy surface for serving.

The training stack's greedy policy (``DDPG.greedy_action``: actor forward,
clip to [0, 1], threshold+renormalize post-processing) is a pure function
of ``(actor_params, obs)``.  Serving needs it

- **batched**: concurrent coordination requests are padded into one device
  call per batch-size bucket (TF-Agents' batched-everything design,
  arXiv 1709.02878) — ``jax.vmap`` over the request axis, so every row's
  answer is mathematically independent of its batch-mates;
- **ahead-of-time compiled**: ``jax.export`` lowers the jitted batched
  policy to a serialized StableHLO module per bucket, so a warm restart
  deserializes instead of re-tracing the whole GNN actor (the 100-second
  share of cold start), and the backend compile of the deserialized module
  is itself skippable via the persistent jax compilation cache.

Pytree plumbing: ``jax.export`` refuses to serialize unregistered pytree
containers (``GraphObs`` is one), so the exported callable takes the obs as
its *flattened leaves* — plain tuples serialize — and rebuilds the tree
inside.  ``ObsTemplate`` owns that flatten/unflatten contract plus the
host-side stack-and-pad staging the batcher uses.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# compile-log names (analysis.sentinels.CompileMonitor keys on these): the
# expensive policy trace happens under POLICY_FN_PREFIX<bucket> — exactly
# once per bucket on a cold start and NEVER on an artifact-cache hit; the
# deserialized module's thin jit wrapper traces under EXEC_FN_PREFIX<bucket>
POLICY_FN_PREFIX = "serve_policy_b"
EXEC_FN_PREFIX = "serve_exec_b"


def policy_fn_name(batch: int) -> str:
    return f"{POLICY_FN_PREFIX}{batch}"


def exec_fn_name(batch: int) -> str:
    return f"{EXEC_FN_PREFIX}{batch}"


def shape_structs(tree):
    """Pytree of ``jax.ShapeDtypeStruct`` mirroring ``tree``'s leaves."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(tuple(jnp.shape(x)),
                                       jnp.asarray(x).dtype), tree)


class ObsTemplate:
    """Flatten/stack/pad contract between host requests and device batches.

    Built once from a sample observation; request payloads must match its
    leaf shapes/dtypes exactly (no silent broadcasting — a malformed
    request fails at staging, inside that request's future, never inside
    the shared device call)."""

    def __init__(self, sample_obs):
        leaves, self.treedef = jax.tree_util.tree_flatten(sample_obs)
        self.leaves: List[np.ndarray] = [np.asarray(x) for x in leaves]
        self.leaf_shapes: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(x.shape) for x in self.leaves)
        self.leaf_dtypes: Tuple[str, ...] = tuple(
            str(x.dtype) for x in self.leaves)

    def flatten(self, obs) -> List[np.ndarray]:
        """One request -> host leaf list (validated against the template)."""
        leaves, treedef = jax.tree_util.tree_flatten(obs)
        if treedef != self.treedef:
            raise ValueError(
                f"request obs tree {treedef} does not match the serving "
                f"template {self.treedef}")
        out = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if tuple(arr.shape) != self.leaf_shapes[i] or \
                    str(arr.dtype) != self.leaf_dtypes[i]:
                raise ValueError(
                    f"request obs leaf {i} is {arr.shape}/{arr.dtype}, "
                    f"template wants {self.leaf_shapes[i]}/"
                    f"{self.leaf_dtypes[i]}")
            out.append(arr)
        return out

    def stack_pad(self, requests: Sequence[List[np.ndarray]],
                  batch: int) -> List[np.ndarray]:
        """Stack ``len(requests) <= batch`` flattened requests into bucket
        arrays ``[batch, ...]``; padding rows repeat the LAST real request
        (valid data, so padded rows can never produce non-finite
        intermediates — and vmap row-independence means their content
        cannot perturb real rows either way; test-asserted)."""
        k = len(requests)
        if not 0 < k <= batch:
            raise ValueError(f"{k} requests into a bucket of {batch}")
        out = []
        for i in range(len(self.leaves)):
            arr = np.empty((batch,) + self.leaf_shapes[i],
                           self.leaf_dtypes[i])
            for j in range(batch):
                arr[j] = requests[min(j, k - 1)][i]
            out.append(arr)
        return out

    def batch_structs(self, batch: int) -> List[jax.ShapeDtypeStruct]:
        return [jax.ShapeDtypeStruct((batch,) + s, d)
                for s, d in zip(self.leaf_shapes, self.leaf_dtypes)]


class GreedyServePolicy:
    """The learned serving tier: ``DDPG.greedy_action`` vmapped per bucket
    and exported to a serialized StableHLO artifact."""

    def __init__(self, ddpg, sample_obs):
        self.ddpg = ddpg
        self.template = ObsTemplate(sample_obs)

    def batched_fn(self, batch: int):
        """(params, *obs_leaves[batch]) -> actions [batch, A]; named per
        bucket so compile telemetry and retrace assertions attribute the
        trace to the serving stack."""
        single = self.ddpg.greedy_action
        treedef = self.template.treedef

        def fn(params, *leaves):
            obs = jax.tree_util.tree_unflatten(treedef, leaves)
            return jax.vmap(single, in_axes=(None, 0))(params, obs)

        fn.__name__ = policy_fn_name(batch)
        return fn

    def export_bucket(self, params, batch: int):
        """AOT-lower the bucket's batched policy: trace + lower happen NOW
        (the expensive share of cold start), returning a
        ``jax.export.Exported`` whose ``.serialize()`` bytes are the
        artifact-cache payload."""
        from jax import export as jax_export

        fn = jax.jit(self.batched_fn(batch))
        return jax_export.export(fn)(shape_structs(params),
                                     *self.template.batch_structs(batch))
