"""On-disk compiled-policy artifact cache.

A cold serving start pays trace + lower + backend compile for every batch
bucket; the serialized ``jax.export`` module makes the first two
persistable.  Entries are keyed by a sha256 over the *cache material* — a
plain-JSON dict of everything the compiled bytes depend on:

- checkpoint fingerprint (content checksum of the weights),
- padded obs leaf shapes/dtypes + the batch bucket,
- precision policy name and the simulator's ``substep_impl`` knob,
- jax/jaxlib versions and the lowering platform,
- the artifact format version.

Any drift in any of these changes the key, so a stale entry is simply a
miss — it can never be *served*.  The residual failure modes are handled
explicitly and never crash a start:

- **corrupt blob** (truncated write, bit rot): ``jax.export.deserialize``
  raises; the server logs, recompiles and overwrites the entry;
- **corrupt/missing meta sidecar**: treated as a miss (the meta is the
  proof the blob matches the material — without it the blob is untrusted);
- **material mismatch under the same key** (hash collision, hand-edited
  file): treated as a miss.

Writes are atomic (temp + rename) so a killed process can't leave a
half-written blob behind a validating meta.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
from typing import Dict, Optional

log = logging.getLogger("gsc_tpu.serve.cache")

# bump when the on-disk layout or the exported calling convention changes
ARTIFACT_FORMAT = 1


def cache_material(*, fingerprint: str, template, batch: int,
                   precision: str, substep_impl: str,
                   graph_mode: bool, gnn_impl: str = "xla") -> Dict:
    """The canonical key material for one bucket's artifact (plain JSON;
    ``template`` is a :class:`~gsc_tpu.serve.policy.ObsTemplate`).
    ``gnn_impl`` matters: the actor is lowered THROUGH the configured GAT
    implementation, so an artifact compiled under one must never be served
    as a hit under the other."""
    import jax
    import jaxlib

    return {
        "format": ARTIFACT_FORMAT,
        "ckpt_fingerprint": fingerprint,
        "obs_leaf_shapes": [list(s) for s in template.leaf_shapes],
        "obs_leaf_dtypes": list(template.leaf_dtypes),
        "batch": int(batch),
        "precision": precision,
        "substep_impl": substep_impl,
        "graph_mode": bool(graph_mode),
        "gnn_impl": gnn_impl,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
    }


class ArtifactCache:
    """Directory of ``<key>.stablehlo`` blobs + ``<key>.json`` meta
    sidecars (key = sha256 of the canonical material JSON)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # keys this process served from or wrote this session — prune()
        # never deletes them, so GC can't evict the entry a live server
        # is (or just started) running on
        self._active: set = set()

    @staticmethod
    def key_of(material: Dict) -> str:
        canon = json.dumps(material, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(canon.encode()).hexdigest()[:40]

    def paths(self, material: Dict):
        key = self.key_of(material)
        return (os.path.join(self.root, key + ".stablehlo"),
                os.path.join(self.root, key + ".json"))

    def load(self, material: Dict) -> Optional[bytes]:
        """Serialized module bytes on a validated hit, else None (miss,
        unreadable entry, or meta/material mismatch — all logged, none
        raised: the caller's fallback is always a fresh compile)."""
        blob_path, meta_path = self.paths(material)
        if not os.path.exists(blob_path):
            return None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError, UnicodeDecodeError) as e:
            log.warning(
                "serve artifact meta unreadable — treating as a miss and "
                "recompiling: path=%s error=%s:%s",
                meta_path, type(e).__name__, e)
            return None
        if not isinstance(meta, dict) or meta.get("material") != material:
            log.warning(
                "serve artifact meta does not describe this material — "
                "treating as a miss: path=%s", meta_path)
            return None
        try:
            with open(blob_path, "rb") as f:
                blob = f.read()
        except OSError as e:
            log.warning("serve artifact unreadable — recompiling: "
                        "path=%s error=%s", blob_path, e)
            return None
        self._active.add(self.key_of(material))
        return blob

    def store(self, material: Dict, blob: bytes) -> str:
        """Atomic write of blob + meta; returns the blob path."""
        blob_path, meta_path = self.paths(material)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, blob_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        from ..obs.sinks import write_atomic_json
        write_atomic_json(meta_path, {"material": material,
                                      "bytes": len(blob)})
        self._active.add(self.key_of(material))
        return blob_path

    def prune(self, keep_latest: int, protect=()) -> list:
        """GC stale entries: keep the ``keep_latest`` most recently
        written blobs (mtime order), delete the rest — hot-swap
        publishing mints one artifact set per checkpoint fingerprint, so
        a long train-while-serve run would otherwise grow the cache one
        generation per published version.

        Never deletes an entry this process loaded or stored
        (``self._active``) or one in ``protect`` (explicit keys).  A
        half-entry — blob without meta (torn write) or meta without blob
        (a previously interrupted prune) — counts as an entry and is
        collectable like any other.  Deletion order is meta first, then
        blob: a concurrent ``load`` that still sees the blob reads a
        missing meta and treats it as a miss, never a half-valid hit.
        Returns the pruned keys."""
        if keep_latest < 0:
            raise ValueError(f"keep_latest must be >= 0: {keep_latest}")
        protected = self._active | set(protect)
        entries = {}
        for path in os.listdir(self.root):
            key, ext = os.path.splitext(path)
            if ext not in (".stablehlo", ".json"):
                continue
            full = os.path.join(self.root, path)
            try:
                mtime = os.path.getmtime(full)
            except OSError:
                continue   # deleted under us (concurrent prune)
            entries[key] = max(entries.get(key, 0.0), mtime)
        keep = sorted(entries, key=lambda k: entries[k],
                      reverse=True)[:keep_latest]
        pruned = []
        for key in entries:
            if key in keep or key in protected:
                continue
            for suffix in (".json", ".stablehlo"):
                try:
                    os.unlink(os.path.join(self.root, key + suffix))
                except OSError:
                    pass
            pruned.append(key)
        if pruned:
            log.info("artifact cache pruned %d stale entr%s (kept %d)",
                     len(pruned), "y" if len(pruned) == 1 else "ies",
                     len(entries) - len(pruned))
        return pruned
