"""gsc_tpu — TPU-native service-coordination RL framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of the GSC
reference (farzad1132/GSC): deep-RL coordination of service function chains
(SFCs) in multi-cloud networks, jointly deciding placement and traffic
scheduling.  Where the reference runs one SimPy discrete-event simulator in
one Python process on CPU (reference: src/rlsp/agents/simple_ddpg.py:106-108),
gsc_tpu runs thousands of vectorized simulator replicas and the full
DDPG/GNN learner on TPU:

- ``gsc_tpu.topology``  — GraphML/YAML -> padded dense topology pytrees
  (replaces coordsim/reader/reader.py's networkx graphs).
- ``gsc_tpu.sim``       — batched fixed-step flow simulator as a pure
  ``lax.scan`` (replaces the SimPy engine in coordsim/simulation/).
- ``gsc_tpu.envs``      — functional reset/step RL environment with the four
  reward objectives (replaces src/rlsp/envs/gym_env.py).
- ``gsc_tpu.models``    — flax GATv2 embedder + actor/critic
  (replaces src/rlsp/agents/models.py).
- ``gsc_tpu.agents``    — jit-compiled DDPG learner with an on-device replay
  buffer (replaces src/rlsp/agents/simple_ddpg.py + buffer.py).
- ``gsc_tpu.parallel``  — mesh/sharding utilities: vmapped env replicas per
  chip, data-parallel learner via shard_map (no analogue in the reference,
  which has no parallelism of any kind).
- ``gsc_tpu.ops``       — Pallas TPU kernels with XLA reference impls.
"""

__version__ = "0.1.0"
