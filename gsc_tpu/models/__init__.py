"""Neural network models (reference: src/rlsp/agents/models.py)."""
from ..ops.gat import dense_adj, gatv2_dense, gatv2_segment
from .gnn import GATv2Conv, GNNEmbedder, masked_mean_pool
from .nets import MLP, Actor, QNetwork, scale_action, unscale_action

__all__ = [
    "GATv2Conv", "GNNEmbedder", "dense_adj", "gatv2_dense", "gatv2_segment",
    "masked_mean_pool", "MLP", "Actor", "QNetwork", "scale_action",
    "unscale_action",
]
