"""Actor and critic networks (reference: src/rlsp/agents/models.py:55-153).

Graph mode: GNN embedding of the padded network graph, concatenated with the
flattened action mask (and the action for the critic), through an MLP; the
actor's output is multiplied by the mask so padded (src, dst) entries are
exactly zero (models.py:146-153).  Flat mode: plain MLPs over the
concatenated observation vectors.  (The reference's flat-mode layer sizing is
internally inconsistent — models.py:80 declares mask-sized inputs its forward
never builds; we size flat inputs correctly instead.)

MLP semantics follow torch_geometric.nn.MLP with norm=None, plain_last=True:
Linear -> ReLU between layers, no activation after the last (so the actor's
output is unbounded; the agent clips to the action box after adding noise,
simple_ddpg.py:195-201).

Mixed precision (AgentConfig.precision -> config.schema.PrecisionPolicy):
the GNN embedder and the Dense stacks compute in the policy's compute
dtype (params stay f32 masters, cast at use; matmuls accumulate f32 via
``preferred_element_type``), and BOTH network outputs — actions and
Q-values — are cast to f32 at the module boundary so exploration noise,
TD targets and Polyak updates always run at full precision.  The "f32"
policy takes the original code paths verbatim (bit-identical).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..config.schema import AgentConfig
from ..env.observations import GraphObs
from .gnn import GNNEmbedder, masked_mean_pool


def _accum_f32_dot_general(lhs, rhs, dimension_numbers, precision=None,
                           preferred_element_type=None):
    """Low-precision operands, f32 MXU accumulation, activation settled
    back to the operand dtype (nn.Dense ``dot_general`` hook)."""
    return jax.lax.dot_general(
        lhs, rhs, dimension_numbers, precision=precision,
        preferred_element_type=jnp.float32).astype(lhs.dtype)


def _dense_kw(dtype: str | None) -> dict:
    """nn.Dense kwargs for a compute dtype; {} = the exact legacy layer."""
    if dtype is None:
        return {}
    return dict(dtype=jnp.dtype(dtype), dot_general=_accum_f32_dot_general)


class MLP(nn.Module):
    """Linear/ReLU stack, plain last layer (torch_geometric MLP, norm=None).
    ``dtype`` is the compute dtype (PrecisionPolicy.mlp_compute); params
    are stored f32 and cast at use, dots accumulate f32."""

    features: Tuple[int, ...]
    dtype: str = None

    @nn.compact
    def __call__(self, x):
        kw = _dense_kw(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, **kw)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


def _embedder(agent: AgentConfig, impl: str) -> GNNEmbedder:
    return GNNEmbedder(hidden=agent.gnn_features,
                       num_layers=agent.gnn_num_layers,
                       num_iter=agent.gnn_num_iter,
                       mean_aggr=agent.gnn_aggr == "mean",
                       impl=impl,
                       compute_dtype=agent.precision_policy.gnn_dtype)


def _node_embedder(agent: AgentConfig, impl: str) -> GNNEmbedder:
    return GNNEmbedder(hidden=agent.gnn_features,
                       num_layers=agent.gnn_num_layers,
                       num_iter=agent.gnn_num_iter,
                       mean_aggr=agent.gnn_aggr == "mean",
                       impl=impl, pool=False,
                       compute_dtype=agent.precision_policy.gnn_dtype)


# action dims (N * C * S * N') above which the monolithic Dense output
# layer stops fitting one chip (a 256-hidden head on the rung-5 393k-dim
# action is a ~100M-param matrix, measured RESOURCE_EXHAUSTED even at B=4
# — BENCH_NOTES r3) and the factored decoder takes over by default
FACTORED_HEAD_THRESHOLD = 16384


def use_factored_head(agent: AgentConfig, action_dim: int) -> bool:
    if agent.factored_head is not None:
        return agent.factored_head and agent.graph_mode
    return agent.graph_mode and action_dim >= FACTORED_HEAD_THRESHOLD


def _check_sched_shape(sched_shape, action_dim: int) -> Tuple[int, ...]:
    if sched_shape is None:
        raise ValueError(
            "factored action head needs sched_shape=(N, C, S, N') "
            "(see EnvLimits.scheduling_shape)")
    n, c, s, n2 = sched_shape
    if n * c * s * n2 != action_dim:
        raise ValueError(f"sched_shape {sched_shape} does not factor "
                         f"action dim {action_dim}")
    return n, c, s, n2


class Actor(nn.Module):
    """Policy network (models.py:97-153).

    Two heads over the shared GNN trunk:

    - monolithic (the reference's shape): graph embedding ++ mask -> MLP ->
      Dense(action_dim).  Exact reference semantics, but the output matrix
      scales as hidden x (N*C*S*N) — ~100M params at rung-5 padding.
    - factored (``use_factored_head``): the schedule is structured
      [src, sfc, sf, dst], so score it as a bilinear form between per-node
      embeddings: h_src -> per-(sfc, sf) query vectors, h_dst -> key
      vectors, logits[n,c,s,m] = <q[n,c,s], k[m]>.  Parameters scale with
      C*S*hidden*key_dim instead of N^2*C*S*hidden (~2000x fewer at
      rung 5), and every op is an einsum on the MXU.

    Both heads multiply by ``obs.mask`` so padded (src, dst) entries are
    exactly zero (models.py:146-153)."""

    agent: AgentConfig
    action_dim: int
    gnn_impl: str = "dense"
    # (N, C, S, N') of the scheduling tensor; required for the factored head
    sched_shape: Tuple[int, int, int, int] = None

    @nn.compact
    def __call__(self, obs):
        mdt = self.agent.precision_policy.mlp_dtype
        if not self.agent.graph_mode:
            out = MLP(tuple(self.agent.actor_hidden_layer_nodes)
                      + (self.action_dim,), dtype=mdt)(obs)
            return out.astype(jnp.float32)
        assert isinstance(obs, GraphObs)
        if use_factored_head(self.agent, self.action_dim):
            n, c, s, n2 = _check_sched_shape(self.sched_shape,
                                             self.action_dim)
            feats = _node_embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            pooled = masked_mean_pool(feats, obs.node_mask)
            # per-src hidden through the configured actor stack (global
            # context broadcast onto every node)
            h = jnp.concatenate(
                [feats, jnp.broadcast_to(
                    pooled.astype(feats.dtype)[..., None, :],
                    feats.shape[:-1] + pooled.shape[-1:])],
                axis=-1)
            h = MLP(tuple(self.agent.actor_hidden_layer_nodes),
                    dtype=mdt)(h)
            h = nn.relu(h)
            g = self.agent.factored_key_dim
            q = nn.Dense(c * s * g, name="query",
                         **_dense_kw(mdt))(h)             # [.., N, C*S*G]
            k = nn.Dense(g, name="key", **_dense_kw(mdt))(feats)  # [.., N', G]
            q = q.reshape(q.shape[:-2] + (n, c, s, g))
            if mdt is None:
                out = jnp.einsum("...ncsg,...mg->...ncsm", q, k)
            else:  # bilinear logits accumulate f32
                out = jnp.einsum("...ncsg,...mg->...ncsm", q, k,
                                 preferred_element_type=jnp.float32)
            out = out.reshape(out.shape[:-4] + (self.action_dim,))
        else:
            emb = _embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            h = jnp.concatenate([emb, obs.mask.astype(emb.dtype)], axis=-1)
            out = MLP(tuple(self.agent.actor_hidden_layer_nodes)
                      + (self.action_dim,), dtype=mdt)(h)
        # actions leave the network in f32 regardless of compute dtype:
        # noise, clipping and replay post-processing stay full precision
        return (out * obs.mask).astype(jnp.float32)


class QNetwork(nn.Module):
    """Critic Q(s, a) (models.py:55-95).

    Factored mode mirrors the actor: the [src, sfc, sf, dst] action is
    contracted against per-node key vectors over the dst axis, giving
    per-src action features that join the node embeddings; a per-node
    Dense + masked mean-pool reduces to a fixed-size vector regardless of
    N, and the configured critic MLP scores it.  (The monolithic head's
    explicit mask input is dropped here: the mask is derived purely from
    node_mask — actions.py action_mask — and node validity already enters
    through the GNN.  Replayed actions DO carry mass on masked entries
    after exploration noise / renormalization; the critic simply reads it
    through the same contraction.)

    The factoring decision keys on ``action.shape[-1]`` at call time, so a
    construction site cannot accidentally pick the monolithic head by
    omitting a field."""

    agent: AgentConfig
    gnn_impl: str = "dense"
    action_dim: int = 0       # informational; the call uses action.shape[-1]
    sched_shape: Tuple[int, int, int, int] = None

    @nn.compact
    def __call__(self, obs, action):
        mdt = self.agent.precision_policy.mlp_dtype
        if not self.agent.graph_mode:
            out = MLP(tuple(self.agent.critic_hidden_layer_nodes) + (1,),
                      dtype=mdt)(
                jnp.concatenate([obs, action.astype(obs.dtype)], axis=-1))
            return out.astype(jnp.float32)
        assert isinstance(obs, GraphObs)
        if use_factored_head(self.agent, action.shape[-1]):
            n, c, s, n2 = _check_sched_shape(self.sched_shape,
                                             action.shape[-1])
            feats = _node_embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            pooled = masked_mean_pool(feats, obs.node_mask)
            g = self.agent.factored_key_dim
            a4 = action.reshape(action.shape[:-1] + (n, c, s, n2))
            k = nn.Dense(g, name="key", **_dense_kw(mdt))(feats)  # [.., N', G]
            if mdt is None:
                a_enc = jnp.einsum("...ncsm,...mg->...ncsg", a4, k)
            else:  # action contraction accumulates f32
                a_enc = jnp.einsum("...ncsm,...mg->...ncsg",
                                   a4.astype(jnp.dtype(mdt)), k,
                                   preferred_element_type=jnp.float32)
            z = jnp.concatenate(
                [feats, a_enc.reshape(a_enc.shape[:-3]
                                      + (c * s * g,)).astype(feats.dtype)],
                axis=-1)
            z = nn.relu(nn.Dense(self.agent.gnn_features, name="src",
                                 **_dense_kw(mdt))(z))
            z = masked_mean_pool(z, obs.node_mask)
            h = jnp.concatenate([pooled, z], axis=-1)
        else:
            emb = _embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            h = jnp.concatenate([emb, obs.mask.astype(emb.dtype),
                                 action.astype(emb.dtype)], axis=-1)
        # Q-values leave in f32: TD targets and losses stay full precision
        return MLP(tuple(self.agent.critic_hidden_layer_nodes) + (1,),
                   dtype=mdt)(h).astype(jnp.float32)


def scale_action(action: jnp.ndarray, low: float = 0.0,
                 high: float = 1.0) -> jnp.ndarray:
    """[low, high] -> [-1, 1] (models.py:127-135)."""
    return 2.0 * (action - low) / (high - low) - 1.0


def unscale_action(scaled: jnp.ndarray, low: float = 0.0,
                   high: float = 1.0) -> jnp.ndarray:
    """[-1, 1] -> [low, high] (models.py:137-144)."""
    return low + 0.5 * (scaled + 1.0) * (high - low)
