"""Actor and critic networks (reference: src/rlsp/agents/models.py:55-153).

Graph mode: GNN embedding of the padded network graph, concatenated with the
flattened action mask (and the action for the critic), through an MLP; the
actor's output is multiplied by the mask so padded (src, dst) entries are
exactly zero (models.py:146-153).  Flat mode: plain MLPs over the
concatenated observation vectors.  (The reference's flat-mode layer sizing is
internally inconsistent — models.py:80 declares mask-sized inputs its forward
never builds; we size flat inputs correctly instead.)

MLP semantics follow torch_geometric.nn.MLP with norm=None, plain_last=True:
Linear -> ReLU between layers, no activation after the last (so the actor's
output is unbounded; the agent clips to the action box after adding noise,
simple_ddpg.py:195-201).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from ..config.schema import AgentConfig
from ..env.observations import GraphObs
from .gnn import GNNEmbedder


class MLP(nn.Module):
    """Linear/ReLU stack, plain last layer (torch_geometric MLP, norm=None)."""

    features: Tuple[int, ...]

    @nn.compact
    def __call__(self, x):
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


def _embedder(agent: AgentConfig, impl: str) -> GNNEmbedder:
    return GNNEmbedder(hidden=agent.gnn_features,
                       num_layers=agent.gnn_num_layers,
                       num_iter=agent.gnn_num_iter,
                       mean_aggr=agent.gnn_aggr == "mean",
                       impl=impl)


class Actor(nn.Module):
    """Policy network (models.py:97-153)."""

    agent: AgentConfig
    action_dim: int
    gnn_impl: str = "dense"

    @nn.compact
    def __call__(self, obs):
        if self.agent.graph_mode:
            assert isinstance(obs, GraphObs)
            emb = _embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            h = jnp.concatenate([emb, obs.mask], axis=-1)
        else:
            h = obs
        out = MLP(tuple(self.agent.actor_hidden_layer_nodes)
                  + (self.action_dim,))(h)
        if self.agent.graph_mode:
            out = out * obs.mask
        return out


class QNetwork(nn.Module):
    """Critic Q(s, a) (models.py:55-95)."""

    agent: AgentConfig
    gnn_impl: str = "dense"

    @nn.compact
    def __call__(self, obs, action):
        if self.agent.graph_mode:
            assert isinstance(obs, GraphObs)
            emb = _embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            h = jnp.concatenate([emb, obs.mask, action], axis=-1)
        else:
            h = jnp.concatenate([obs, action], axis=-1)
        return MLP(tuple(self.agent.critic_hidden_layer_nodes) + (1,))(h)


def scale_action(action: jnp.ndarray, low: float = 0.0,
                 high: float = 1.0) -> jnp.ndarray:
    """[low, high] -> [-1, 1] (models.py:127-135)."""
    return 2.0 * (action - low) / (high - low) - 1.0


def unscale_action(scaled: jnp.ndarray, low: float = 0.0,
                   high: float = 1.0) -> jnp.ndarray:
    """[-1, 1] -> [low, high] (models.py:137-144)."""
    return low + 0.5 * (scaled + 1.0) * (high - low)
