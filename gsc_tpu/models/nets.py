"""Actor and critic networks (reference: src/rlsp/agents/models.py:55-153).

Graph mode: GNN embedding of the padded network graph, concatenated with the
flattened action mask (and the action for the critic), through an MLP; the
actor's output is multiplied by the mask so padded (src, dst) entries are
exactly zero (models.py:146-153).  Flat mode: plain MLPs over the
concatenated observation vectors.  (The reference's flat-mode layer sizing is
internally inconsistent — models.py:80 declares mask-sized inputs its forward
never builds; we size flat inputs correctly instead.)

MLP semantics follow torch_geometric.nn.MLP with norm=None, plain_last=True:
Linear -> ReLU between layers, no activation after the last (so the actor's
output is unbounded; the agent clips to the action box after adding noise,
simple_ddpg.py:195-201).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from ..config.schema import AgentConfig
from ..env.observations import GraphObs
from .gnn import GNNEmbedder, masked_mean_pool


class MLP(nn.Module):
    """Linear/ReLU stack, plain last layer (torch_geometric MLP, norm=None)."""

    features: Tuple[int, ...]

    @nn.compact
    def __call__(self, x):
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


def _embedder(agent: AgentConfig, impl: str) -> GNNEmbedder:
    return GNNEmbedder(hidden=agent.gnn_features,
                       num_layers=agent.gnn_num_layers,
                       num_iter=agent.gnn_num_iter,
                       mean_aggr=agent.gnn_aggr == "mean",
                       impl=impl)


def _node_embedder(agent: AgentConfig, impl: str) -> GNNEmbedder:
    return GNNEmbedder(hidden=agent.gnn_features,
                       num_layers=agent.gnn_num_layers,
                       num_iter=agent.gnn_num_iter,
                       mean_aggr=agent.gnn_aggr == "mean",
                       impl=impl, pool=False)


# action dims (N * C * S * N') above which the monolithic Dense output
# layer stops fitting one chip (a 256-hidden head on the rung-5 393k-dim
# action is a ~100M-param matrix, measured RESOURCE_EXHAUSTED even at B=4
# — BENCH_NOTES r3) and the factored decoder takes over by default
FACTORED_HEAD_THRESHOLD = 16384


def use_factored_head(agent: AgentConfig, action_dim: int) -> bool:
    if agent.factored_head is not None:
        return agent.factored_head and agent.graph_mode
    return agent.graph_mode and action_dim >= FACTORED_HEAD_THRESHOLD


def _check_sched_shape(sched_shape, action_dim: int) -> Tuple[int, ...]:
    if sched_shape is None:
        raise ValueError(
            "factored action head needs sched_shape=(N, C, S, N') "
            "(see EnvLimits.scheduling_shape)")
    n, c, s, n2 = sched_shape
    if n * c * s * n2 != action_dim:
        raise ValueError(f"sched_shape {sched_shape} does not factor "
                         f"action dim {action_dim}")
    return n, c, s, n2


class Actor(nn.Module):
    """Policy network (models.py:97-153).

    Two heads over the shared GNN trunk:

    - monolithic (the reference's shape): graph embedding ++ mask -> MLP ->
      Dense(action_dim).  Exact reference semantics, but the output matrix
      scales as hidden x (N*C*S*N) — ~100M params at rung-5 padding.
    - factored (``use_factored_head``): the schedule is structured
      [src, sfc, sf, dst], so score it as a bilinear form between per-node
      embeddings: h_src -> per-(sfc, sf) query vectors, h_dst -> key
      vectors, logits[n,c,s,m] = <q[n,c,s], k[m]>.  Parameters scale with
      C*S*hidden*key_dim instead of N^2*C*S*hidden (~2000x fewer at
      rung 5), and every op is an einsum on the MXU.

    Both heads multiply by ``obs.mask`` so padded (src, dst) entries are
    exactly zero (models.py:146-153)."""

    agent: AgentConfig
    action_dim: int
    gnn_impl: str = "dense"
    # (N, C, S, N') of the scheduling tensor; required for the factored head
    sched_shape: Tuple[int, int, int, int] = None

    @nn.compact
    def __call__(self, obs):
        if not self.agent.graph_mode:
            return MLP(tuple(self.agent.actor_hidden_layer_nodes)
                       + (self.action_dim,))(obs)
        assert isinstance(obs, GraphObs)
        if use_factored_head(self.agent, self.action_dim):
            n, c, s, n2 = _check_sched_shape(self.sched_shape,
                                             self.action_dim)
            feats = _node_embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            pooled = masked_mean_pool(feats, obs.node_mask)
            # per-src hidden through the configured actor stack (global
            # context broadcast onto every node)
            h = jnp.concatenate(
                [feats, jnp.broadcast_to(pooled[..., None, :],
                                         feats.shape[:-1] + pooled.shape[-1:])],
                axis=-1)
            h = MLP(tuple(self.agent.actor_hidden_layer_nodes))(h)
            h = nn.relu(h)
            g = self.agent.factored_key_dim
            q = nn.Dense(c * s * g, name="query")(h)      # [.., N, C*S*G]
            k = nn.Dense(g, name="key")(feats)            # [.., N', G]
            q = q.reshape(q.shape[:-2] + (n, c, s, g))
            out = jnp.einsum("...ncsg,...mg->...ncsm", q, k)
            out = out.reshape(out.shape[:-4] + (self.action_dim,))
        else:
            emb = _embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            h = jnp.concatenate([emb, obs.mask], axis=-1)
            out = MLP(tuple(self.agent.actor_hidden_layer_nodes)
                      + (self.action_dim,))(h)
        return out * obs.mask


class QNetwork(nn.Module):
    """Critic Q(s, a) (models.py:55-95).

    Factored mode mirrors the actor: the [src, sfc, sf, dst] action is
    contracted against per-node key vectors over the dst axis, giving
    per-src action features that join the node embeddings; a per-node
    Dense + masked mean-pool reduces to a fixed-size vector regardless of
    N, and the configured critic MLP scores it.  (The monolithic head's
    explicit mask input is dropped here: the mask is derived purely from
    node_mask — actions.py action_mask — and node validity already enters
    through the GNN.  Replayed actions DO carry mass on masked entries
    after exploration noise / renormalization; the critic simply reads it
    through the same contraction.)

    The factoring decision keys on ``action.shape[-1]`` at call time, so a
    construction site cannot accidentally pick the monolithic head by
    omitting a field."""

    agent: AgentConfig
    gnn_impl: str = "dense"
    action_dim: int = 0       # informational; the call uses action.shape[-1]
    sched_shape: Tuple[int, int, int, int] = None

    @nn.compact
    def __call__(self, obs, action):
        if not self.agent.graph_mode:
            return MLP(tuple(self.agent.critic_hidden_layer_nodes) + (1,))(
                jnp.concatenate([obs, action], axis=-1))
        assert isinstance(obs, GraphObs)
        if use_factored_head(self.agent, action.shape[-1]):
            n, c, s, n2 = _check_sched_shape(self.sched_shape,
                                             action.shape[-1])
            feats = _node_embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            pooled = masked_mean_pool(feats, obs.node_mask)
            g = self.agent.factored_key_dim
            a4 = action.reshape(action.shape[:-1] + (n, c, s, n2))
            k = nn.Dense(g, name="key")(feats)            # [.., N', G]
            a_enc = jnp.einsum("...ncsm,...mg->...ncsg", a4, k)
            z = jnp.concatenate(
                [feats, a_enc.reshape(a_enc.shape[:-3] + (c * s * g,))],
                axis=-1)
            z = nn.relu(nn.Dense(self.agent.gnn_features, name="src")(z))
            z = masked_mean_pool(z, obs.node_mask)
            h = jnp.concatenate([pooled, z], axis=-1)
        else:
            emb = _embedder(self.agent, self.gnn_impl)(
                obs.nodes, obs.edge_index, obs.edge_mask, obs.node_mask)
            h = jnp.concatenate([emb, obs.mask, action], axis=-1)
        return MLP(tuple(self.agent.critic_hidden_layer_nodes) + (1,))(h)


def scale_action(action: jnp.ndarray, low: float = 0.0,
                 high: float = 1.0) -> jnp.ndarray:
    """[low, high] -> [-1, 1] (models.py:127-135)."""
    return 2.0 * (action - low) / (high - low) - 1.0


def unscale_action(scaled: jnp.ndarray, low: float = 0.0,
                   high: float = 1.0) -> jnp.ndarray:
    """[-1, 1] -> [low, high] (models.py:137-144)."""
    return low + 0.5 * (scaled + 1.0) * (high - low)
