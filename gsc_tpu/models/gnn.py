"""GATv2 graph modules (flax) — TPU-native.

The reference embeds ≤24-node network graphs with torch-geometric
``GATv2Conv`` layers (src/rlsp/agents/models.py:10-53): an encoder conv, then
``num_layers-1`` process convs applied ``num_iter`` times with *shared
weights* (weight-tied message passing), ReLU between, masked mean-pool
readout.  Single attention head, configurable neighborhood aggregation
(``mean`` in sample_agent.yaml:32), self-loops included.

The graph here is dense and padded (MAX_NODES fixed), so attention is a
masked [N, N] softmax — batches of graphs map straight onto the MXU as
batched matmuls, with no gather/scatter in the hot path.  The attention math
lives in ``gsc_tpu.ops`` with three parity-tested implementations (dense XLA,
edge-list segment-sum, fused Pallas kernel) selected by ``impl``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.gat import dense_adj, gatv2_dense, gatv2_segment, project


class GATv2Conv(nn.Module):
    """One GATv2 layer (reference: torch_geometric GATv2Conv as used at
    models.py:22-27).  ``impl``: 'dense' (default), 'segment' or 'pallas'.

    ``compute_dtype`` (PrecisionPolicy.gnn_compute, e.g. "bfloat16") sets
    the attention compute precision; parameters are always stored f32
    (master copies) and cast at use, and ``None`` keeps the exact legacy
    f32 path."""

    features: int
    mean_aggr: bool = True
    impl: str = "dense"
    compute_dtype: str = None

    @nn.compact
    def __call__(self, x, adj=None, edge_index=None, edge_mask=None,
                 node_mask=None):
        f_in = x.shape[-1]
        cd = self.compute_dtype
        glorot = nn.initializers.glorot_uniform()
        w_l = self.param("w_l", glorot, (f_in, self.features))
        b_l = self.param("b_l", nn.initializers.zeros, (self.features,))
        w_r = self.param("w_r", glorot, (f_in, self.features))
        b_r = self.param("b_r", nn.initializers.zeros, (self.features,))
        att = self.param("att", glorot, (self.features, 1))[:, 0]
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        if self.impl == "segment":
            fn = lambda xi, ei, em, nm: gatv2_segment(
                xi, ei, em, nm, w_l, b_l, w_r, b_r, att, bias,
                self.mean_aggr, compute_dtype=cd)
            for _ in range(x.ndim - 2):
                fn = jax.vmap(fn)
            return fn(x, edge_index, edge_mask, node_mask)
        if self.impl == "pallas":
            from ..ops.pallas_gat import gatv2_pallas
            xl = project(x, w_l, b_l, cd)
            xr = project(x, w_r, b_r, cd)
            return gatv2_pallas(xl, xr, att, bias, adj, self.mean_aggr)
        return gatv2_dense(x, adj, w_l, b_l, w_r, b_r, att, bias,
                           self.mean_aggr, compute_dtype=cd)


def masked_mean_pool(x: jnp.ndarray, node_mask: jnp.ndarray) -> jnp.ndarray:
    """global_mean_pool over real nodes (models.py:44, 53).  The readout
    reduction always ACCUMULATES in f32 (PrecisionPolicy accum contract) —
    a no-op for f32 inputs, a widening cast for bf16 activations."""
    xf = x.astype(jnp.float32)
    m = node_mask.astype(xf.dtype)[..., None]
    return (xf * m).sum(axis=-2) / jnp.maximum(m.sum(axis=-2), 1.0)


class GNNEmbedder(nn.Module):
    """Encoder conv + weight-tied process convs iterated ``num_iter`` times,
    ReLU between convs, masked mean-pool readout (models.py:10-53).  Defaults
    follow sample_agent.yaml:29-32 (22 features, 2 layers, 2 iters, mean)."""

    hidden: int = 22
    num_layers: int = 2
    num_iter: int = 2
    mean_aggr: bool = True
    impl: str = "dense"
    pool: bool = True   # False: return per-node features at the readout
                        # point (factored action heads read node embeddings)
    compute_dtype: str = None  # PrecisionPolicy.gnn_compute; None = f32

    @nn.compact
    def __call__(self, nodes, edge_index, edge_mask, node_mask):
        adj = None
        if self.impl != "segment":
            adj = dense_adj(edge_index, edge_mask, node_mask)
        kw = dict(adj=adj, edge_index=edge_index, edge_mask=edge_mask,
                  node_mask=node_mask)
        conv_args = dict(features=self.hidden, mean_aggr=self.mean_aggr,
                         impl=self.impl, compute_dtype=self.compute_dtype)

        def readout(x):
            return masked_mean_pool(x, node_mask) if self.pool else x

        x = GATv2Conv(**conv_args, name="encoder")(nodes, **kw)
        x = nn.relu(x)
        if self.num_layers == 1:
            return readout(x)
        # instantiating each process conv once and calling it num_iter times
        # shares its parameters — the reference's weight tying (models.py:44-53)
        process = [GATv2Conv(**conv_args, name=f"process_{i}")
                   for i in range(self.num_layers - 1)]
        for it in range(self.num_iter):
            for i, conv in enumerate(process):
                x = conv(x, **kw)
                if i == self.num_layers - 2 and it == self.num_iter - 1:
                    return readout(x)
                x = nn.relu(x)
