"""Canonical benchmark service catalogs.

The reference ships these as YAML assets (configs/service_functions/abc.yaml
and variants); programmatic builders keep one source of truth for the
benchmark scenarios, the tests, and the driver entry points.
"""
from __future__ import annotations

from .schema import ServiceConfig, ServiceFunction


def abc_service() -> ServiceConfig:
    """The reference's abc chain: a->b->c, 5 ms mean processing each
    (configs/service_functions/abc.yaml:4-21)."""
    sf = lambda n: ServiceFunction(name=n, processing_delay_mean=5.0,
                                   processing_delay_stdev=0.0)
    return ServiceConfig(sfc_list={"sfc_1": ("a", "b", "c")},
                         sf_list={n: sf(n) for n in "abc"})


def mixed_service() -> ServiceConfig:
    """Mixed SFC catalog for BASELINE config 5 — two chains over a shared
    5-SF pool: abc (3 x 5 ms) + de (8 ms + 2 ms)."""
    mk = lambda n, d: ServiceFunction(name=n, processing_delay_mean=d,
                                      processing_delay_stdev=0.0)
    return ServiceConfig(
        sfc_list={"sfc_1": ("a", "b", "c"), "sfc_2": ("d", "e")},
        sf_list={"a": mk("a", 5.0), "b": mk("b", 5.0), "c": mk("c", 5.0),
                 "d": mk("d", 8.0), "e": mk("e", 2.0)})
