"""Typed configuration schema for the five config namespaces.

The reference spreads configuration over five YAML namespaces — agent
(configs/config/agent/sample_agent.yaml), simulator
(configs/config/simulator/sample_config.yaml), service functions
(configs/service_functions/abc.yaml), scheduler (configs/config/scheduler.yaml)
and a GraphML network — validated ad hoc in src/rlsp/agents/main.py:249-276
and coordsim/reader/reader.py:74-111, with component implementations selected
by ``eval()`` of class-name strings (coordsim/simulation/simulatorparams.py:29-38,
siminterface/simulator.py:130).

Here every namespace is a frozen dataclass of plain Python scalars/tuples so
configs are hashable and can be closed over by ``jax.jit``.  Component
selection goes through a string->callable registry (``gsc_tpu.config.registry``)
instead of ``eval``.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Mapping, Optional, Tuple


class FrozenMap(Mapping):
    """Immutable, hashable mapping (insertion-ordered) so configs that carry
    mappings stay usable as static jit arguments."""

    __slots__ = ("_items", "_lookup")

    def __init__(self, data):
        items = tuple(data.items()) if isinstance(data, Mapping) else tuple(data)
        object.__setattr__(self, "_items", items)
        object.__setattr__(self, "_lookup", dict(items))

    def __getitem__(self, key):
        return self._lookup[key]

    def __iter__(self):
        return (k for k, _ in self._items)

    def __len__(self):
        return len(self._items)

    def __hash__(self):
        return hash(self._items)

    def __eq__(self, other):
        if isinstance(other, FrozenMap):
            return self._items == other._items
        if isinstance(other, Mapping):
            return dict(self._items) == dict(other)
        return NotImplemented

    def __repr__(self):
        return f"FrozenMap({dict(self._items)!r})"

SUPPORTED_OBJECTIVES = ("prio-flow", "soft-deadline", "soft-deadline-exp", "weighted")
# Dtypes a mixed-precision compute/replay slot may take.  float16 is
# deliberately absent: bf16 shares f32's exponent range so the policy needs
# no loss scaling — the property the whole PrecisionPolicy design leans on.
_COMPUTE_DTYPES = ("float32", "bfloat16")


@dataclass(frozen=True)
class PrecisionPolicy:
    """End-to-end dtype policy for the training stack.

    The policy separates three concerns per module family:

    - ``param_dtype``: the MASTER storage dtype of network parameters and
      optimizer state.  Always float32 — Polyak target updates at
      tau=1e-4 (AgentConfig.target_model_update) underflow to no-ops in
      bf16's 8-bit mantissa, and Adam's second-moment EMA degrades the
      same way, so masters never leave f32 (the Podracer/MindSpeed-RL
      "mixed compute, full-precision state" recipe).
    - ``gnn_compute`` / ``mlp_compute``: the activation/matmul dtype of
      the GATv2 embedder and the actor/critic Dense stacks.  bf16 halves
      the dominant [B, N, N, F] attention intermediate and runs the MXU
      at ~2x f32 throughput; every contraction still ACCUMULATES in f32
      via ``preferred_element_type`` and the attention softmax runs on
      f32 logits.
    - ``replay_dtype``: storage dtype of replay obs/action leaves
      (agents/buffer.py) — halves the largest HBM resident.  Rewards and
      done flags always stay f32 so TD-target scale survives.

    Network OUTPUTS (actions, Q-values) are always f32: exploration
    noise, TD targets and target-network soft updates run at full
    precision regardless of the compute dtype.  ``f32`` everywhere is the
    default and is bit-identical to a stack with no dtype policy at all
    (the pre-policy code paths are taken verbatim when a slot is f32).
    """

    name: str = "f32"
    param_dtype: str = "float32"
    gnn_compute: str = "float32"
    mlp_compute: str = "float32"
    accum_dtype: str = "float32"
    output_dtype: str = "float32"
    replay_dtype: str = "float32"

    def __post_init__(self):
        for slot in ("param_dtype", "accum_dtype", "output_dtype"):
            if getattr(self, slot) != "float32":
                raise ValueError(
                    f"{slot} must be float32 (f32 master params/accumulators"
                    f"/outputs are the policy contract), got "
                    f"{getattr(self, slot)!r}")
        for slot in ("gnn_compute", "mlp_compute", "replay_dtype"):
            if getattr(self, slot) not in _COMPUTE_DTYPES:
                raise ValueError(
                    f"{slot} must be one of {_COMPUTE_DTYPES}, got "
                    f"{getattr(self, slot)!r}")

    # -- consumers key on None = "take the legacy exact-f32 code path" --
    @property
    def gnn_dtype(self) -> Optional[str]:
        return None if self.gnn_compute == "float32" else self.gnn_compute

    @property
    def mlp_dtype(self) -> Optional[str]:
        return None if self.mlp_compute == "float32" else self.mlp_compute

    @property
    def replay_cast_dtype(self) -> Optional[str]:
        return None if self.replay_dtype == "float32" else self.replay_dtype

    @property
    def mixed(self) -> bool:
        return any(getattr(self, s) != "float32"
                   for s in ("gnn_compute", "mlp_compute", "replay_dtype"))


# Named policies selectable via AgentConfig.precision / `cli train
# --precision` / `bench.py --precision`.  "f32" is bit-identical to the
# pre-policy stack; "bf16" is the TPU mixed-precision recipe.
PRECISION_POLICIES = {
    "f32": PrecisionPolicy(name="f32"),
    "bf16": PrecisionPolicy(name="bf16", gnn_compute="bfloat16",
                            mlp_compute="bfloat16",
                            replay_dtype="bfloat16"),
}


def precision_policy(name: str) -> PrecisionPolicy:
    """Resolve a policy name (AgentConfig.precision) to its PrecisionPolicy."""
    try:
        return PRECISION_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown precision {name!r} (expected one of "
            f"{tuple(PRECISION_POLICIES)})") from None

# Observation components supported by the env (reference:
# src/rlsp/envs/simulator_wrapper.py:178-235 builds these three vectors).
SUPPORTED_OBSERVATIONS = ("ingress_traffic", "node_load", "node_cap")
DROP_REASONS = ("TTL", "DECISION", "LINK_CAP", "NODE_CAP")


@dataclass(frozen=True)
class ServiceFunction:
    """One SF's properties (reference: coordsim/reader/reader.py:74-111)."""

    name: str
    processing_delay_mean: float = 1.0
    processing_delay_stdev: float = 1.0
    startup_delay: float = 0.0
    # Registry key of the resource demand function load -> demanded capacity
    # (reference: dynamically imported per-SF ``resource_function``,
    # coordsim/reader/reader.py:60-72; default is identity, reader.py:86-87).
    resource_function_id: str = "default"


@dataclass(frozen=True)
class ServiceConfig:
    """SFC catalog: chains of SFs (reference: configs/service_functions/abc.yaml)."""

    # sfc name -> ordered tuple of SF names
    sfc_list: Mapping[str, Tuple[str, ...]]
    sf_list: Mapping[str, ServiceFunction]

    def __post_init__(self):
        # normalize to hashable mappings (dataclass is frozen -> object.__setattr__)
        object.__setattr__(self, "sfc_list", FrozenMap(self.sfc_list))
        object.__setattr__(self, "sf_list", FrozenMap(self.sf_list))
        for sfc, chain in self.sfc_list.items():
            for sf in chain:
                if sf not in self.sf_list:
                    raise ValueError(f"SFC {sfc!r} references unknown SF {sf!r}")

    @property
    def num_sfcs(self) -> int:
        return len(self.sfc_list)

    @property
    def max_chain_len(self) -> int:
        return max(len(c) for c in self.sfc_list.values())

    @property
    def sf_names(self) -> Tuple[str, ...]:
        return tuple(self.sf_list.keys())

    @property
    def sfc_names(self) -> Tuple[str, ...]:
        return tuple(self.sfc_list.keys())


@dataclass(frozen=True)
class MMPPState:
    """One state of the two-state Markov-modulated Poisson arrival process
    (reference: coordsim/simulation/simulatorparams.py:100-121, 143-176)."""

    name: str
    inter_arr_mean: float
    switch_p: float


@dataclass(frozen=True)
class SimConfig:
    """Simulator/traffic configuration
    (reference: configs/config/simulator/sample_config.yaml +
    coordsim/simulation/simulatorparams.py:13-131).
    """

    inter_arrival_mean: float = 10.0
    deterministic_arrival: bool = True
    flow_dr_mean: float = 1.0
    flow_dr_stdev: float = 0.0
    flow_size_shape: float = 0.001
    deterministic_size: bool = True
    run_duration: float = 100.0
    ttl_choices: Tuple[float, ...] = (100.0,)
    vnf_timeout: float = 100.0

    # Capacity overrides (reference: coordsim/reader/builders.py:9-26)
    force_link_cap: Optional[float] = None
    force_node_cap: Optional[Tuple[float, float]] = None

    # MMPP two-state arrival model (reference: simulatorparams.py:100-121)
    use_states: bool = False
    init_state: Optional[str] = None
    rand_init_state: bool = False
    states: Tuple[MMPPState, ...] = ()

    # Trace-driven traffic (reference: coordsim/trace_processor/trace_processor.py)
    trace_path: Optional[str] = None

    # Traffic prediction: observations show *upcoming* ingress traffic
    # instead of the last interval's (reference 'prediction' flag plumbing,
    # siminterface/simulator.py:47 + traffic_predictor.py:22-56)
    prediction: bool = False

    # Control granularity (replaces the eval()-resolved controller_class,
    # siminterface/simulator.py:130): "duration" = one (placement, schedule)
    # action per interval (DurationController); "per_flow" = per-flow
    # destination decisions with place-on-decision + idle-VNF GC
    # (FlowController).  The external decision-maker semantics
    # (external_decision_maker.py) are the per_flow path's ext_decisions.
    controller: str = "duration"

    # --- TPU engine parameters (new; no reference analogue) ---
    # Substep quantum in ms for the fixed-step lax.scan engine.  The reference
    # engine is continuous-time event-driven (SimPy); with default configs all
    # delays are integer ms so dt=1.0 reproduces it exactly.
    dt: float = 1.0
    # Max concurrently active flows per replica (flow-table slots).
    max_flows: int = 128
    # Ring-buffer horizon (in substeps) for delayed capacity release.
    release_horizon: int = 256
    # Iterations of the monotone greedy-admission refinement (within-substep
    # sequential capacity-admission semantics).
    admission_iters: int = 3
    # Rank levels for exact sequential WRR among same-substep collisions.
    wrr_rank_levels: int = 4
    # lax.scan unroll factor for the substep loop: >1 trades compile time
    # (and a run_duration/dt divisibility requirement) for less scan
    # overhead on a substep made of many small fusions.
    scan_unroll: int = 1
    # Substep implementation (mirrors AgentConfig.gnn_impl): "xla" = the
    # hand-fused one-hot XLA pipeline (default, the reference-parity
    # workhorse); "pallas" = the substep MEGAKERNEL — the whole
    # admission/release chain as ONE pallas_call per substep
    # (gsc_tpu/ops/pallas_substep.py; interpret-mode on CPU, bit-exact vs
    # "xla" by construction and by the `pytest -m megakernel` suite).
    # Per-flow control (controller="per_flow") stays on the XLA path.
    substep_impl: str = "xla"

    def __post_init__(self):
        if self.use_states and len(self.states) != 2:
            raise ValueError("MMPP model requires exactly 2 states")
        if self.run_duration <= 0 or self.dt <= 0:
            raise ValueError("run_duration and dt must be positive")
        if not self.ttl_choices:
            raise ValueError("TTL must be set in config file")  # simulatorparams.py:41
        if self.controller not in ("duration", "per_flow"):
            raise ValueError(
                f"unknown controller {self.controller!r} (expected "
                "'duration' or 'per_flow'; reference spellings "
                "DurationController/FlowController are mapped by the "
                "loader)")
        if self.substep_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown substep_impl {self.substep_impl!r} "
                "(expected 'xla' or 'pallas')")
        if self.substep_impl == "pallas" and self.controller == "per_flow":
            # the megakernel covers the batch-control (DurationController)
            # substep only; per-flow external decisions would silently run
            # the XLA body anyway — fail fast instead of faking the knob
            raise ValueError(
                "substep_impl='pallas' supports only controller='duration' "
                "(per-flow control runs the XLA substep)")
        if self.scan_unroll < 1:
            raise ValueError("scan_unroll must be >= 1")

    @property
    def substeps_per_run(self) -> int:
        n = round(self.run_duration / self.dt)
        if abs(n * self.dt - self.run_duration) > 1e-9:
            raise ValueError("run_duration must be a multiple of dt")
        return int(n)


@dataclass(frozen=True)
class AgentConfig:
    """Agent/learning configuration
    (reference: configs/config/agent/sample_agent.yaml, validated in
    src/rlsp/agents/main.py:249-276).
    """

    observation_space: Tuple[str, ...] = ("ingress_traffic", "node_load", "node_cap")
    # (the reference also parses link_observation_space, but its only
    # consumer is commented out, environment_limits.py:88 — not carried)
    graph_mode: bool = True
    shuffle_nodes: bool = False
    episode_steps: int = 200
    agent_type: str = "DDPG"

    # GNN (reference: sample_agent.yaml:29-32, models.py:10-53)
    gnn_features: int = 22
    gnn_num_layers: int = 2
    gnn_num_iter: int = 2
    gnn_aggr: str = "mean"
    # GNN embedder implementation: "dense" (XLA-fused masked dense
    # attention) or "pallas" (fused TPU kernel, gsc_tpu/ops/pallas_gat.py;
    # interpret-mode on CPU).  New key — the reference's torch-geometric
    # GATv2 has no such switch.
    gnn_impl: str = "dense"
    actor_hidden_layer_nodes: Tuple[int, ...] = (256,)
    critic_hidden_layer_nodes: Tuple[int, ...] = (64,)
    # Factored (per-node bilinear) action head for large scheduling
    # tensors.  None = automatic: enabled in graph mode when the action
    # dim crosses models/nets.py:FACTORED_HEAD_THRESHOLD (the monolithic
    # Dense output layer OOMs one chip near rung-5 padding).  New keys —
    # the reference's monolithic head (models.py:97-153) has no analogue.
    factored_head: Optional[bool] = None
    factored_key_dim: int = 32

    # objective / reward (reference: gym_env.py:300-380)
    objective: str = "weighted"
    flow_weight: float = 1.0
    delay_weight: float = 0.0
    node_weight: float = 0.0
    instance_weight: float = 0.0
    target_success: float | str = "auto"
    soft_deadline: float = 10.0
    dropoff: float = 10.0

    # replay / exploration / optimization (reference: sample_agent.yaml:38-65)
    mem_limit: int = 10000
    rand_mu: float = 0.0
    rand_sigma: float = 0.3
    # single warmup horizon: the reference only ever consumes
    # nb_steps_warmup_critic (simple_ddpg.py:183, 308); the *_actor twin in
    # its sample yaml is dead and not carried
    nb_steps_warmup_critic: int = 200
    gamma: float = 0.99
    target_model_update: float = 1e-4
    learning_rate: float = 1e-3
    batch_size: int = 100
    # gradient steps per end-of-episode learn burst; None = episode_steps
    # (the reference's train-at-episode-end schedule, simple_ddpg.py:
    # 307-325).  A sweep knob: large-B replica runs gather B x
    # episode_steps transitions per episode, so the reference's burst
    # length under-trains relative to data collected.
    learn_steps: Optional[int] = None

    # action post-processing (reference: simple_ddpg.py:130-131)
    schedule_threshold: float = 0.1

    # Precision policy name (PRECISION_POLICIES): "f32" (default,
    # bit-identical to the dtype-unaware stack) or "bf16" (mixed-precision
    # compute + replay with f32 master params/optimizer state).  New key —
    # the reference is implicitly f32 end to end.
    precision: str = "f32"

    def __post_init__(self):
        # the reference's agent_type dispatch (main.py:374-381) is broken
        # upstream (SAC_Agent is never defined); here unknown types fail fast
        if self.agent_type != "DDPG":
            raise ValueError(
                f"unsupported agent_type {self.agent_type!r} (only DDPG)")
        if self.gnn_num_layers < 1 or self.gnn_num_iter < 1:
            raise ValueError("gnn_num_layers and gnn_num_iter must be >= 1")
        if self.gnn_impl not in ("dense", "pallas"):
            raise ValueError(f"unknown gnn_impl {self.gnn_impl!r}")
        if self.objective not in SUPPORTED_OBJECTIVES:
            raise ValueError(
                f"Unexpected objective {self.objective}. Must be in {SUPPORTED_OBJECTIVES}."
            )
        for obs in self.observation_space:
            if obs not in SUPPORTED_OBSERVATIONS:
                raise ValueError(f"Unsupported observation component {obs!r}")
        if self.objective == "prio-flow" and self.target_success != "auto":
            if not 0 <= float(self.target_success) <= 1:
                raise ValueError("target_success must be in [0,1] or 'auto'")
        if self.learn_steps is not None and self.learn_steps < 1:
            # 0 would silently run zero gradient steps per learn burst;
            # use None (= episode_steps) for the reference schedule
            raise ValueError("learn_steps must be >= 1 (or None)")
        if self.precision not in PRECISION_POLICIES:
            raise ValueError(
                f"unknown precision {self.precision!r} (expected one of "
                f"{tuple(PRECISION_POLICIES)})")

    @property
    def precision_policy(self) -> PrecisionPolicy:
        """The resolved dtype policy (models/agents consume this)."""
        return PRECISION_POLICIES[self.precision]


@dataclass(frozen=True)
class SchedulerConfig:
    """Topology schedule across training (reference: configs/config/scheduler.yaml,
    consumed by src/rlsp/envs/gym_env.py:103-128)."""

    training_network_files: Tuple[str, ...]
    inference_network: str
    period: int = 10

    def __post_init__(self):
        if not self.training_network_files:
            raise ValueError("training_network_files must not be empty")
        if self.period <= 0:
            raise ValueError("period must be positive")


@dataclass(frozen=True)
class EnvLimits:
    """Fixed padded dimensions enabling cross-topology generalization
    (reference: src/rlsp/envs/environment_limits.py:9-106 and the hard-coded
    24-node/37-edge limits at gym_env.py:59-66)."""

    max_nodes: int = 24
    max_edges: int = 37
    num_sfcs: int = 1
    # max chain length — sizes the schedule tensor's SF-POSITION axis
    max_sfs: int = 3
    # distinct SFs in the catalog — sizes all per-(node, SF-id) state
    # (placement, load, proc tables).  None = max_sfs (single-chain configs,
    # where position and id coincide).  A mixed catalog (e.g. abc + de)
    # needs the two axes separated: chain positions stay <= max_sfs while
    # SF ids run over the whole pool.
    num_sfs: Optional[int] = None

    @property
    def sf_pool(self) -> int:
        return self.num_sfs if self.num_sfs is not None else self.max_sfs

    @property
    def scheduling_shape(self) -> Tuple[int, int, int, int]:
        # (src node, sfc, sf, dst node) — environment_limits.py:44-51
        return (self.max_nodes, self.num_sfcs, self.max_sfs, self.max_nodes)

    @property
    def action_dim(self) -> int:
        n = 1
        for s in self.scheduling_shape:
            n *= s
        return n

    @classmethod
    def for_service(cls, service: ServiceConfig, max_nodes: int = 24,
                    max_edges: int = 37) -> "EnvLimits":
        return cls(max_nodes=max_nodes, max_edges=max_edges,
                   num_sfcs=service.num_sfcs, max_sfs=service.max_chain_len,
                   num_sfs=len(service.sf_list))


def replace(cfg, **kw):
    """Convenience dataclasses.replace passthrough."""
    return dataclasses.replace(cfg, **kw)
