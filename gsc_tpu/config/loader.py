"""YAML loaders for the five config namespaces.

Mirrors the reference's loaders — get_config/get_sfc/get_sf
(coordsim/reader/reader.py:37-111), agent-config load+validate
(src/rlsp/agents/main.py:249-276), scheduler load
(src/rlsp/agents/main.py:73-75) — but parses into the frozen dataclasses of
``gsc_tpu.config.schema``.  Accepts the reference's YAML key spelling so
existing config files keep working (e.g. ``GNN_features`` -> gnn_features).
"""
from __future__ import annotations

from typing import Any, Dict

import yaml

from .schema import (
    AgentConfig,
    MMPPState,
    SchedulerConfig,
    ServiceConfig,
    ServiceFunction,
    SimConfig,
)


def _load_yaml(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return yaml.safe_load(f)


def load_service(path: str,
                 resource_functions_path: str = None) -> ServiceConfig:
    """Parse an SFC/SF catalog yaml (reference: reader.py:47-111).

    ``resource_functions_path`` loads user resource-function plugins first
    (registry.load_resource_function_plugins — the explicit analogue of
    the reference's per-SF dynamic imports, reader.py:60-72).  Like the
    reference, an SF naming an unknown function falls back to "default"
    with a warning rather than failing the run (reader.py:99-104)."""
    import logging

    from .registry import has_resource_function, load_resource_function_plugins

    if resource_functions_path:
        load_resource_function_plugins(resource_functions_path)
    data = _load_yaml(path)
    sfc_list = {name: tuple(chain) for name, chain in data["sfc_list"].items()}
    sf_list = {}
    for name, details in data["sf_list"].items():
        details = details or {}
        rf_id = details.get("resource_function_id", "default")
        if not has_resource_function(rf_id):
            logging.getLogger("gsc_tpu.config").warning(
                "SF %s names unknown resource function %r (pass "
                "--resource-functions-path to load plugins); using default",
                name, rf_id)
            rf_id = "default"
        sf_list[name] = ServiceFunction(
            name=name,
            processing_delay_mean=float(details.get("processing_delay_mean", 1.0)),
            processing_delay_stdev=float(details.get("processing_delay_stdev", 1.0)),
            startup_delay=float(details.get("startup_delay", 0.0)),
            resource_function_id=rf_id,
        )
    return ServiceConfig(sfc_list=sfc_list, sf_list=sf_list)


def load_sim(path: str, **overrides) -> SimConfig:
    """Parse a simulator config yaml (reference: simulatorparams.py:13-131)."""
    cfg = _load_yaml(path)
    kw: Dict[str, Any] = {}
    det = cfg.get("deterministic", None)
    if det is not None:
        kw["deterministic_arrival"] = bool(det)
        kw["deterministic_size"] = bool(det)
    # deterministic_arrival/size override 'deterministic' (simulatorparams.py:88-92)
    for key in ("deterministic_arrival", "deterministic_size"):
        if key in cfg:
            kw[key] = bool(cfg[key])
    if "deterministic_arrival" not in kw or "deterministic_size" not in kw:
        raise ValueError(
            "'deterministic_arrival' or 'deterministic_size' are not set in simulator config."
        )  # simulatorparams.py:93-94
    for key in ("inter_arrival_mean", "flow_dr_mean", "flow_dr_stdev",
                "flow_size_shape", "run_duration", "vnf_timeout", "dt"):
        if key in cfg:
            kw[key] = float(cfg[key])
    if "ttl_choices" in cfg:
        kw["ttl_choices"] = tuple(float(t) for t in cfg["ttl_choices"])
    else:
        raise ValueError("TTL must be set in config file")  # simulatorparams.py:41
    if "force_link_cap" in cfg:
        kw["force_link_cap"] = float(cfg["force_link_cap"])
    if "force_node_cap" in cfg:
        kw["force_node_cap"] = tuple(float(c) for c in cfg["force_node_cap"])
    if cfg.get("use_states"):
        kw["use_states"] = True
        kw["init_state"] = cfg["init_state"]
        kw["rand_init_state"] = bool(cfg.get("rand_init_state", False))
        kw["states"] = tuple(
            MMPPState(name=k, inter_arr_mean=float(v["inter_arr_mean"]),
                      switch_p=float(v["switch_p"]))
            for k, v in cfg["states"].items()
        )
    if "trace_path" in cfg:
        kw["trace_path"] = cfg["trace_path"]
    if "prediction" in cfg:
        kw["prediction"] = bool(cfg["prediction"])
    for key in ("max_flows", "release_horizon",
                "admission_iters", "wrr_rank_levels", "scan_unroll"):
        if key in cfg:
            kw[key] = int(cfg[key])
    if "substep_impl" in cfg:
        kw["substep_impl"] = str(cfg["substep_impl"])
    if "controller_class" in cfg:
        kw["controller"] = {"DurationController": "duration",
                            "FlowController": "per_flow"}.get(
            cfg["controller_class"], cfg["controller_class"])
    if "controller" in cfg:
        # the rebuild's native spelling; silently ignoring it would make
        # `controller: per_flow` run the duration controller
        if "controller_class" in cfg and kw["controller"] != cfg["controller"]:
            raise ValueError(
                f"conflicting controller_class={cfg['controller_class']!r} "
                f"and controller={cfg['controller']!r} in {path}")
        kw["controller"] = cfg["controller"]
    kw.update(overrides)
    return SimConfig(**kw)


# Reference agent-yaml key -> AgentConfig field.
_AGENT_KEYMAP = {
    "GNN_features": "gnn_features",
    "GNN_num_layers": "gnn_num_layers",
    "GNN_num_iter": "gnn_num_iter",
    "GNN_aggr": "gnn_aggr",
}


def load_agent(path: str, **overrides) -> AgentConfig:
    """Parse an agent config yaml (reference: sample_agent.yaml +
    src/rlsp/agents/main.py:249-276 validation)."""
    cfg = _load_yaml(path)
    kw: Dict[str, Any] = {}
    fields = AgentConfig.__dataclass_fields__
    for key, val in cfg.items():
        key = _AGENT_KEYMAP.get(key, key)
        if key not in fields:
            continue  # tolerate unknown keys like the reference
        if isinstance(val, list):
            val = tuple(val)
        kw[key] = val
    kw.update(overrides)
    return AgentConfig(**kw)


def _resolve_network_path(p: str, anchor: str) -> str:
    """Resolve a scheduler network path the way the reference experiment
    layout expects: verbatim (cwd-relative / absolute) first, then against
    each ancestor of the scheduler yaml.  Reference scheduler files carry
    repo-root-relative paths like ``configs/networks/...`` (scheduler.yaml
    sits at configs/config/), which only resolve when running FROM the
    repo root — the ancestor walk makes the same file drop-in from any
    working directory."""
    import os

    if os.path.isabs(p) or os.path.exists(p):
        return p
    d = os.path.dirname(os.path.abspath(anchor))
    while True:
        cand = os.path.join(d, p)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return p  # unresolvable: let load_topology raise with the raw path
        d = parent


def load_scheduler(path: str) -> SchedulerConfig:
    """Parse a scheduler yaml (reference: configs/config/scheduler.yaml)."""
    cfg = _load_yaml(path)
    return SchedulerConfig(
        training_network_files=tuple(
            _resolve_network_path(p, path)
            for p in cfg["training_network_files"]),
        inference_network=_resolve_network_path(cfg["inference_network"],
                                                path),
        period=int(cfg.get("period", 10)),
    )
