"""Component registry: named, typed component lookup replacing ``eval``.

The reference resolves component implementations by ``eval()``-ing class-name
strings from config against pkgutil-flattened package namespaces
(coordsim/simulation/flowsimulator.py:30-40, siminterface/simulator.py:130,
coordsim/controller/__init__.py:9-17).  That pattern is both unsafe and
incompatible with jit tracing.  Here components are plain callables (or
factories of callables) registered under string keys; configs carry the key.

Registries:
- ``resource_functions``: load -> demanded node capacity, used by the node
  admission check (reference: coordsim/flow_processors/base_processor.py:24-35;
  per-SF functions dynamically imported at reader.py:60-72, default identity).
  Entries must be jax-traceable elementwise functions.
"""
from __future__ import annotations

from typing import Callable, Dict

_RESOURCE_FUNCTIONS: Dict[str, Callable] = {}


def register_resource_function(name: str):
    def deco(fn):
        _RESOURCE_FUNCTIONS[name] = fn
        return fn
    return deco


def get_resource_function(name: str) -> Callable:
    try:
        return _RESOURCE_FUNCTIONS[name]
    except KeyError:
        raise KeyError(
            f"Unknown resource function {name!r}; registered: {sorted(_RESOURCE_FUNCTIONS)}"
        ) from None


def has_resource_function(name: str) -> bool:
    return name in _RESOURCE_FUNCTIONS


def load_resource_function_plugins(path: str) -> list:
    """Import user resource-function modules and register them.

    Parity with the reference's dynamic per-SF imports
    (coordsim/reader/reader.py:60-72: ``<id>.py`` files in a
    ``resource_functions_path`` exposing a ``resource_function(load)``
    callable), minus the implicitness — plugins load only when the user
    passes the path (cli ``--resource-functions-path`` / the
    ``load_service`` argument).

    ``path`` is a ``.py`` file or a directory of them.  Each module may
    either call ``gsc_tpu.config.registry.register_resource_function``
    itself, or simply define ``resource_function(load)`` reference-style —
    then it is registered under the file stem.  Functions must be
    jax-traceable elementwise maps (they run inside the jitted node
    admission loop).  Returns the list of names registered."""
    import importlib.util
    import os

    files = ([os.path.join(path, f) for f in sorted(os.listdir(path))
              if f.endswith(".py")] if os.path.isdir(path) else [path])
    registered = []
    for fp in files:
        stem = os.path.splitext(os.path.basename(fp))[0]
        before = set(_RESOURCE_FUNCTIONS)
        spec = importlib.util.spec_from_file_location(
            f"gsc_tpu_resource_plugin_{stem}", fp)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        registered += sorted(set(_RESOURCE_FUNCTIONS) - before)
        if stem not in _RESOURCE_FUNCTIONS and hasattr(module,
                                                       "resource_function"):
            _RESOURCE_FUNCTIONS[stem] = module.resource_function
            registered.append(stem)
    return registered


@register_resource_function("default")
def _identity(load):
    """Default resource demand = load (reference: reader.py:86-87)."""
    return load


@register_resource_function("overhead")
def _overhead(load):
    """Fixed base cost while instantiated + 20% per-unit overhead — the
    shape of the reference's pluggable per-SF ``resource_function`` files
    (reader.py:60-72 loads arbitrary load->demand callables).  jnp-traceable
    and zero when the instance carries no load, so drained instances free
    their base cost."""
    import jax.numpy as jnp

    return jnp.where(load > 0, 1.0 + 1.2 * load, 0.0)
