from .schema import (
    AgentConfig,
    EnvLimits,
    MMPPState,
    PRECISION_POLICIES,
    PrecisionPolicy,
    SchedulerConfig,
    ServiceConfig,
    ServiceFunction,
    SimConfig,
    SUPPORTED_OBJECTIVES,
    SUPPORTED_OBSERVATIONS,
    DROP_REASONS,
    precision_policy,
)
from .loader import load_agent, load_scheduler, load_service, load_sim
from .registry import get_resource_function, register_resource_function

__all__ = [
    "AgentConfig", "EnvLimits", "MMPPState", "PrecisionPolicy",
    "PRECISION_POLICIES", "precision_policy", "SchedulerConfig",
    "ServiceConfig", "ServiceFunction", "SimConfig",
    "SUPPORTED_OBJECTIVES", "SUPPORTED_OBSERVATIONS", "DROP_REASONS",
    "load_agent", "load_scheduler", "load_service", "load_sim",
    "get_resource_function", "register_resource_function",
]
