// Native traffic pre-generation — the host-side hot path.
//
// Every episode, every env replica needs a freshly sampled TrafficSchedule
// (arrival times / rates / sizes / TTLs / SFC / egress choices per ingress
// node).  The reference samples flows one at a time inside SimPy processes
// (coordsim/flow_generators/default_generator.py:18-60) or pregenerates
// python lists (simulatorparams.py:185-247); our numpy path
// (gsc_tpu/sim/traffic.py) is a per-flow Python loop.  At bench scale
// (256 replicas x ~1000s of flows per episode) that loop is minutes of
// host time per training run — this C++ implementation generates the same
// schedule layout in microseconds and is loaded via ctypes
// (gsc_tpu/native/__init__.py), with the numpy path as a fallback.
//
// Semantics mirror the numpy generator exactly (structure, not bitstreams —
// each path is internally seeded-reproducible):
//  - per-(interval, ingress) arrival means, NaN = ingress inactive; an
//    inactive ingress skips forward to its next active interval
//  - flow generated first, then inter-arrival sleep (flowsimulator.py:63-70)
//  - deterministic or exponential inter-arrival (default_generator.py:21-25)
//  - dr ~ Normal(mean, stdev); size = shape (det) or Pareto(shape)+1;
//    joint rejection-resample of negatives (default_generator.py:47-60)
//  - duration = size / dr * 1000 ms (flow.py:33)
//  - TTL/SFC/egress uniform choices (default_generator.py:30-40)
//  - records sorted by arrival time; at most `capacity` kept
//
// Build: g++ -O2 -shared -fPIC -o _traffic.so traffic_gen.cpp

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <numeric>
#include <random>
#include <vector>

extern "C" {

// returns number of flows written (<= capacity)
int gsc_generate_flows(
    uint64_t seed,
    int episode_steps, double run_duration,
    int n_nodes, const double* means,  // [episode_steps * n_nodes]
    double dr_mean, double dr_stdev,
    double size_shape, int det_arrival, int det_size,
    const double* ttl_choices, int n_ttl,
    int n_sfcs,
    const int* egress_nodes, int n_egress,
    int capacity,
    double* out_times, int* out_ingress, double* out_drs, double* out_durs,
    double* out_ttls, int* out_sfcs, int* out_egs) {
  std::mt19937_64 rng(seed);
  std::normal_distribution<double> dr_dist(dr_mean, dr_stdev);
  std::exponential_distribution<double> unit_exp(1.0);
  std::uniform_real_distribution<double> unif(0.0, 1.0);

  const double horizon = episode_steps * run_duration;
  std::vector<double> times;
  std::vector<int> ingress;
  std::vector<double> drs, durs, ttls;
  std::vector<int> sfcs, egs;

  for (int node = 0; node < n_nodes; ++node) {
    // only nodes with any active interval generate (ingress marking is
    // encoded by non-NaN means)
    double t = 0.0;
    while (t < horizon) {
      int k = static_cast<int>(t / run_duration);
      if (k >= episode_steps) break;
      double mean = means[k * n_nodes + node];
      if (std::isnan(mean)) {
        // deactivated: jump to the next active interval, if any
        int nxt = -1;
        for (int j = k + 1; j < episode_steps; ++j) {
          if (!std::isnan(means[j * n_nodes + node])) { nxt = j; break; }
        }
        if (nxt < 0) break;
        t = nxt * run_duration;
        continue;
      }
      // joint rejection-resample of (dr, size)
      double dr, size;
      for (;;) {
        dr = dr_stdev > 0.0 ? dr_dist(rng) : dr_mean;
        if (det_size) {
          size = size_shape;
        } else {
          // Pareto(shape)+1 via inverse CDF, matching numpy's
          // rng.pareto(a) = (1-u)^(-1/a) - 1, then +1
          double u = unif(rng);
          size = std::pow(1.0 - u, -1.0 / size_shape);  // pareto + 1
        }
        if (dr >= 0.0 && size >= 0.0) break;
      }
      times.push_back(t);
      ingress.push_back(node);
      drs.push_back(dr);
      durs.push_back(dr > 0.0 ? size / dr * 1000.0 : 0.0);
      ttls.push_back(ttl_choices[static_cast<int>(unif(rng) * n_ttl) % n_ttl]);
      sfcs.push_back(static_cast<int>(unif(rng) * n_sfcs) % n_sfcs);
      egs.push_back(n_egress > 0
                        ? egress_nodes[static_cast<int>(unif(rng) * n_egress)
                                       % n_egress]
                        : -1);
      t += det_arrival ? mean : mean * unit_exp(rng);
    }
  }

  // stable sort by arrival time
  std::vector<int> order(times.size());
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int a, int b) { return times[a] < times[b]; });

  int n = static_cast<int>(std::min<size_t>(order.size(), capacity));
  for (int i = 0; i < n; ++i) {
    int j = order[i];
    out_times[i] = times[j];
    out_ingress[i] = ingress[j];
    out_drs[i] = drs[j];
    out_durs[i] = durs[j];
    out_ttls[i] = ttls[j];
    out_sfcs[i] = sfcs[j];
    out_egs[i] = egs[j];
  }
  return n;
}

}  // extern "C"
