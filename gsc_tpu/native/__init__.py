"""Native (C++) host-path accelerators, loaded via ctypes.

The TPU compute path is JAX/XLA/Pallas; this package holds the *host* hot
paths in C++ — currently the per-episode traffic pre-generation
(traffic_gen.cpp), which the pure-numpy fallback implements as a per-flow
Python loop (gsc_tpu/sim/traffic.py).  The shared object is built on first
use with g++ (no pip/pybind dependencies); any build or load failure falls
back to numpy silently.  Set ``GSC_TPU_NO_NATIVE=1`` to force the fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "traffic_gen.cpp")
_SO = os.path.join(_DIR, "_traffic.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    if os.environ.get("GSC_TPU_NO_NATIVE") == "1":
        _failed = True
        return None
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                if not _build():
                    _failed = True
                    return None
            lib = ctypes.CDLL(_SO)
            lib.gsc_generate_flows.restype = ctypes.c_int
            lib.gsc_generate_flows.argtypes = [
                ctypes.c_uint64,
                ctypes.c_int, ctypes.c_double,
                ctypes.c_int, ctypes.POINTER(ctypes.c_double),
                ctypes.c_double, ctypes.c_double,
                ctypes.c_double, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_double), ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
            ]
            _lib = lib
        except Exception:
            _failed = True
    return _lib


def generate_flows_native(seed: int, means: np.ndarray, run_duration: float,
                          dr_mean: float, dr_stdev: float, size_shape: float,
                          det_arrival: bool, det_size: bool,
                          ttl_choices: np.ndarray, n_sfcs: int,
                          egress_nodes: np.ndarray, capacity: int):
    """-> (times, ingress, drs, durs, ttls, sfcs, egs) ndarrays of length n,
    or None when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    steps, n_nodes = means.shape
    means = np.ascontiguousarray(means, np.float64)
    ttl = np.ascontiguousarray(ttl_choices, np.float64)
    eg = np.ascontiguousarray(egress_nodes, np.int32)
    times = np.empty(capacity, np.float64)
    ingress = np.empty(capacity, np.int32)
    drs = np.empty(capacity, np.float64)
    durs = np.empty(capacity, np.float64)
    ttls = np.empty(capacity, np.float64)
    sfcs = np.empty(capacity, np.int32)
    egs = np.empty(capacity, np.int32)
    pd = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    pi = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
    n = lib.gsc_generate_flows(
        ctypes.c_uint64(seed), steps, run_duration, n_nodes, pd(means),
        dr_mean, dr_stdev, size_shape, int(det_arrival), int(det_size),
        pd(ttl), len(ttl), n_sfcs, pi(eg), len(eg), capacity,
        pd(times), pi(ingress), pd(drs), pd(durs), pd(ttls), pi(sfcs),
        pi(egs))
    return (times[:n], ingress[:n], drs[:n], durs[:n], ttls[:n], sfcs[:n],
            egs[:n])
