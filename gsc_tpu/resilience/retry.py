"""Bounded exponential-backoff retry for transient dispatch failures.

A single transient ``XlaRuntimeError`` (a tunnel hiccup, a momentarily
wedged backend) used to kill an entire training run; production systems
retry such failures with backoff before escalating (MindSpeed RL,
arXiv:2507.19017).  Only *transient* error types are retried — programming
errors, shape mismatches and injected hard faults propagate immediately.

Donation caveat: the trainer's dispatch closures re-run end-to-end on
retry.  A failure raised at call entry (the common transient shape, and
where the fault injector raises) leaves the donated carries untouched; a
fault that aborted mid-program may have invalidated them, in which case
the retry itself fails fast with XLA's donation error and propagates after
the bounded attempts — retry never hides a genuinely broken carry.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional, Tuple

log = logging.getLogger("gsc_tpu.resilience.retry")


class TransientDispatchError(RuntimeError):
    """An injected ``XlaRuntimeError``-like transient dispatch failure
    (``FaultPlan`` site ``dispatch_transient``)."""


def transient_error_types() -> Tuple[type, ...]:
    """Error types worth retrying: the injected transient class plus the
    runtime's real XLA error type(s) when importable."""
    types = [TransientDispatchError]
    try:   # newer jax spells it jax.errors.JaxRuntimeError
        import jax
        err = getattr(getattr(jax, "errors", None), "JaxRuntimeError", None)
        if isinstance(err, type):
            types.append(err)
    except Exception:
        pass
    try:   # the concrete xla_extension type most versions raise
        from jaxlib.xla_extension import XlaRuntimeError
        types.append(XlaRuntimeError)
    except Exception:
        pass
    return tuple(types)


@dataclasses.dataclass
class RetryPolicy:
    """``attempts`` TOTAL tries; sleep ``min(cap_s, base_s * 2**k)`` before
    retry k (k >= 1)."""

    attempts: int = 3
    base_s: float = 0.05
    cap_s: float = 2.0

    def backoff_s(self, attempt: int) -> float:
        return min(self.cap_s, self.base_s * (2.0 ** max(attempt - 1, 0)))


def call_with_retry(fn: Callable, policy: Optional[RetryPolicy] = None,
                    on_retry: Optional[Callable[[int, BaseException, float],
                                                None]] = None):
    """Run ``fn()`` with bounded exponential backoff on transient errors.

    ``on_retry(attempt, exc, backoff_s)`` fires before each re-attempt
    (attempt numbering starts at 1 for the first RETRY) — the trainer
    hangs its structured ``recovery`` event off it.  The final failure
    propagates unchanged."""
    policy = policy or RetryPolicy()
    transient = transient_error_types()
    for attempt in range(1, max(policy.attempts, 1) + 1):
        try:
            return fn()
        except transient as e:
            if attempt >= policy.attempts:
                log.error("transient dispatch failure persisted through "
                          "%d attempts: %r", attempt, e)
                raise
            delay = policy.backoff_s(attempt)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            log.warning("transient dispatch failure (attempt %d/%d): %r — "
                        "backing off %.2fs", attempt, policy.attempts, e,
                        delay)
            time.sleep(delay)
