"""Preemption-safe checkpointing: checksums, rotation, last-good pointer.

``cli train --ckpt-interval N`` snapshots the verified last-good learner
state + replay every N episodes through a :class:`CheckpointManager`:

- every save carries a content checksum in its ``.meta.json`` sidecar
  (``utils.checkpoint.checkpoint_checksum``) and is re-validated before
  the pointer moves.  The checksum is derived from the bytes orbax wrote,
  so what it proves is that the checkpoint READ BACK equals what was
  recorded: the post-save check catches damage landing between write and
  pointer update (and the injected ``ckpt_corrupt`` fault) and re-saves
  once; the real protection is at RESUME time, where truncation, bit rot
  or a half-finished save from a killed process fails validation and
  falls back — a writer that serialized garbage in the first place is
  out of scope (that is what the in-memory rollback guard's verified
  snapshots are for);
- ``last_good.json`` is an atomically-rewritten pointer to the newest
  VALIDATED checkpoint;
- retention keeps the newest ``retain`` checkpoints (the pointer target is
  never pruned), so a long run cannot fill the disk.

``--resume auto`` (:func:`find_resumable`) walks a result tree for
checksummed sidecars, newest-episode first, and returns the first
checkpoint whose checksum still validates — falling back past a corrupted
newest checkpoint to the previous good one.

NOTE: importing this module pulls in the orbax/agents stack; it is
deliberately NOT re-exported from ``gsc_tpu.resilience`` (see the package
docstring).
"""
from __future__ import annotations

import json
import logging
import os
import shutil
from typing import List, Optional, Tuple

import numpy as np

from ..obs.sinks import write_atomic_json
from ..utils.checkpoint import (read_checkpoint_meta, save_checkpoint,
                                verify_checkpoint)

log = logging.getLogger("gsc_tpu.resilience.ckpt")

POINTER_NAME = "last_good.json"
_META_SUFFIX = ".meta.json"


def corrupt_checkpoint(path: str) -> Optional[str]:
    """Truncate the largest file under an on-disk checkpoint to half its
    size — the ``ckpt_corrupt`` fault's disk damage (also what a
    mid-preemption kill of a non-atomic writer leaves behind).  Returns
    the damaged file's path, or None when there was nothing to damage."""
    target, target_size = None, -1
    for root, _, files in os.walk(path):
        for name in files:
            fp = os.path.join(root, name)
            size = os.path.getsize(fp)
            if size > target_size:
                target, target_size = fp, size
    if target is None:
        return None
    with open(target, "r+b") as f:
        f.truncate(max(target_size // 2, 1))
    return target


class CheckpointManager:
    """Rotating checksummed checkpoints under one root directory.

    ``save`` writes ``<root>/ep<episode>``, validates the written bytes,
    re-saves once on validation failure (emitting a ``recovery`` event
    through ``obs``), updates the ``last_good.json`` pointer and prunes
    beyond ``retain``.  ``fault_plan`` wires the ``ckpt_corrupt``
    injection site."""

    def __init__(self, root: str, retain: int = 3,
                 meta: Optional[dict] = None, fault_plan=None, obs=None):
        self.root = os.path.abspath(root)
        self.retain = max(int(retain), 1)
        self.meta = dict(meta or {})
        self.fault_plan = fault_plan
        self.obs = obs

    def _path(self, episode: int) -> str:
        return os.path.join(self.root, f"ep{int(episode):08d}")

    @property
    def pointer_path(self) -> str:
        return os.path.join(self.root, POINTER_NAME)

    def save(self, state, buffer, episode: int) -> Optional[str]:
        """Checkpoint ``episode`` completed episodes; returns the path on
        success, None when even the re-save failed validation (the pointer
        then still names the previous good checkpoint)."""
        os.makedirs(self.root, exist_ok=True)
        path = self._path(episode)

        # NOT named `write`: gsc-lint resolves call edges by bare name, and
        # half the traced codebase calls `.write(...)` — a closure named
        # `write` here would drag this whole host-side module into the
        # jit-reachability set and flag its int()/os calls as host syncs
        def write_ckpt():
            return save_checkpoint(
                path, state, buffer=buffer,
                extra={"episode": np.asarray(episode, np.int32)},
                meta={**self.meta, "episode": int(episode)}, checksum=True)

        write_ckpt()
        if self.fault_plan is not None:
            spec = self.fault_plan.fire("ckpt_corrupt", episode,
                                        at_or_after=True)
            if spec is not None:
                damaged = corrupt_checkpoint(path)
                log.warning("fault ckpt_corrupt: damaged %s", damaged)
        if not verify_checkpoint(path):
            # a corrupted write must never become the resume target: say
            # so (structured), and re-save once — disk-full or a genuinely
            # broken writer fails again and keeps the previous pointer
            if self.obs is not None:
                self.obs.recovery(episode=episode, site="checkpoint",
                                  fault="checksum_mismatch",
                                  action="resave",
                                  detail=f"validation failed for {path}; "
                                         "rewriting once")
            else:
                log.warning("checkpoint %s failed checksum validation — "
                            "re-saving once", path)
            write_ckpt()
            if not verify_checkpoint(path):
                log.error("checkpoint %s failed validation twice — "
                          "keeping previous last-good pointer", path)
                return None
        write_atomic_json(self.pointer_path, {
            "path": path, "episode": int(episode),
            "checksum": read_checkpoint_meta(path).get("checksum")})
        self._prune(keep=path)
        return path

    def _prune(self, keep: str):
        """Drop all but the newest ``retain`` checkpoints (and never the
        pointer target / just-written one)."""
        entries: List[Tuple[int, str]] = []
        for name in os.listdir(self.root):
            full = os.path.join(self.root, name)
            if name.startswith("ep") and os.path.isdir(full):
                try:
                    entries.append((int(name[2:]), full))
                except ValueError:
                    continue
        entries.sort(reverse=True)
        for _, full in entries[self.retain:]:
            if os.path.abspath(full) == os.path.abspath(keep):
                continue
            shutil.rmtree(full, ignore_errors=True)
            try:
                os.unlink(full + _META_SUFFIX)
            except OSError:
                pass

    def latest_valid(self) -> Optional[str]:
        return find_resumable(self.root)


def find_resumable(search_root: str) -> Optional[str]:
    """Newest checkpoint under ``search_root`` (recursive) whose content
    checksum validates — the ``--resume auto`` resolver.

    Candidates are directories with a ``.meta.json`` sidecar carrying a
    ``checksum`` field (periodic saves, preemption snapshots, and final
    ``cli train`` checkpoints all qualify), ordered newest first by the
    sidecar's recorded episode then mtime.  An invalid candidate (damaged
    bytes, stale sidecar) is logged and skipped — the previous good one
    wins."""
    search_root = os.path.abspath(search_root)
    candidates: List[Tuple[int, float, str]] = []
    for root, _, files in os.walk(search_root):
        for name in files:
            if not name.endswith(_META_SUFFIX):
                continue
            sidecar = os.path.join(root, name)
            ckpt = sidecar[:-len(_META_SUFFIX)]
            meta = read_checkpoint_meta(ckpt)
            if not meta.get("checksum") or not os.path.isdir(ckpt):
                continue
            try:
                mtime = os.path.getmtime(sidecar)
            except OSError:
                continue
            candidates.append((int(meta.get("episode", -1)), mtime, ckpt))
    for episode, _, ckpt in sorted(candidates, reverse=True):
        if verify_checkpoint(ckpt):
            log.info("resume auto: %s (episode %d) validates", ckpt,
                     episode)
            return ckpt
        log.warning("resume auto: %s failed checksum validation — "
                    "falling back to the previous checkpoint", ckpt)
    return None
