"""Deterministic fault injection: the ``FaultPlan`` grammar and firing.

Production RL systems treat worker failure as normal operation (Podracer,
arXiv:2104.06272) and co-design the training loop with the platform's
failure modes (MindSpeed RL, arXiv:2507.19017) — but a recovery path that
has never executed is a recovery path that does not work.  A ``FaultPlan``
injects *named* faults at *named* sites keyed by episode index, so every
self-healing path in the trainer has a test (and a CI chaos stage) that
actually exercises it:

==================== =====================================================
site                 effect when the keyed episode is reached
==================== =====================================================
``prefetch_die``     the episode prefetcher's producer thread raises while
                     staging the keyed episode (surfaced on the consumer's
                     next ``get``; the trainer restarts the prefetcher)
``slow_episode``     the producer sleeps ``arg`` seconds (default 1.0)
                     before staging the keyed episode — long enough to trip
                     the watchdog, whose escalation interrupts/restarts the
                     prefetcher (the sleep aborts early on prefetcher stop)
``dispatch_transient`` episode dispatch raises a transient
                     ``XlaRuntimeError``-like failure once; the retry layer
                     backs off and re-dispatches
``nan_grads``        the learner state entering the keyed episode is
                     poisoned with NaN (the effect of a NaN gradient
                     update); the on-device all-finite guard detects it at
                     drain and the trainer rolls back
``ckpt_corrupt``     the first periodic checkpoint written at-or-after the
                     keyed episode is corrupted on disk; checksum
                     validation catches it and the manager re-saves
==================== =====================================================

The async fleet (decoupled actor/learner, ``run_async``) adds sites keyed
by actor episode, learn-burst index or published version — the failure
modes a Sebulba-style fleet meets when workers move to their own
processes and chips:

==================== =====================================================
site                 effect when the keyed point is reached
==================== =====================================================
``actor_die``        the keyed actor thread raises at entry to the keyed
                     episode (``actor_die@a0:3``: actor 0, episode 3);
                     the ActorSupervisor restarts it from its episode
                     counter, degrading the fleet past the restart budget
``ring_poison``      the keyed episode's first produced block is NaN-
                     poisoned before it enters the channel
                     (``ring_poison@5``); the learner's drain-boundary
                     finite check quarantines it instead of ingesting
``publish_corrupt``  the keyed published version is corrupted in flight
                     (``publish_corrupt@v2``): file-backed publishes get
                     a flipped byte in the blob (fingerprint validation
                     parks it), in-process publishes deliver NaN leaves
                     (the watcher's finite gate parks it) — either way no
                     watcher ever adopts the version
``watcher_stall``    the keyed actor's version poll raises at the keyed
                     episode (``watcher_stall@a1:4``, optional ``:arg``
                     stall seconds first); the actor skips the adoption
                     and continues on its current weights
``learner_transient`` learn-burst dispatch raises the retryable transient
                     class at entry to the keyed BURST index
                     (``learner_transient@7``); the retry layer backs off
                     and re-dispatches
==================== =====================================================

Grammar (``--fault-plan`` / env ``GSC_FAULT_PLAN``)::

    plan  := entry (";" entry)*
    entry := site "@" key [":" arg]
    key   := episode                  (episode/burst-keyed sites)
           | "a" actor ":" episode   (actor-keyed: actor_die, watcher_stall)
           | "v" version             (version-keyed: publish_corrupt)

e.g. ``prefetch_die@1;nan_grads@3;slow_episode@2:1.5`` or the async chaos
leg ``actor_die@a0:1;ring_poison@2;learner_transient@3``.  Each entry
fires exactly ONCE (thread-safe), which is what makes the recovery paths
convergent: a restarted prefetcher (or actor) re-staging the same episode
does not re-hit the fault.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import List, Optional

log = logging.getLogger("gsc_tpu.resilience.faults")

SITES = ("prefetch_die", "slow_episode", "dispatch_transient", "nan_grads",
         "ckpt_corrupt", "actor_die", "ring_poison", "publish_corrupt",
         "watcher_stall", "learner_transient")

# per-site key domains: actor-keyed sites REQUIRE the a<actor>:<episode>
# form, version-keyed the v<version> form; everything else is a plain
# int (an episode index, or a learn-burst index for learner_transient)
ACTOR_KEYED = ("actor_die", "watcher_stall")
VERSION_KEYED = ("publish_corrupt",)
BURST_KEYED = ("learner_transient",)

ENV_VAR = "GSC_FAULT_PLAN"


class FaultInjected(RuntimeError):
    """An injected (non-transient) fault — e.g. the prefetcher producer's
    death.  Distinct from the transient class so the retry layer never
    retries a fault that models a hard failure."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    episode: int                     # episode / burst / version key
    arg: Optional[float] = None
    actor: Optional[int] = None      # actor-keyed sites only
    fired_at: Optional[int] = None   # key the fault actually fired at

    @property
    def fired(self) -> bool:
        return self.fired_at is not None

    @property
    def key(self) -> str:
        """The entry's key in grammar form (``3``, ``a0:3``, ``v2``)."""
        if self.actor is not None:
            return f"a{self.actor}:{self.episode}"
        if self.site in VERSION_KEYED:
            return f"v{self.episode}"
        return str(self.episode)


class FaultPlan:
    """Parsed fault schedule; ``fire`` is the single (locked) gate every
    injection site calls — marking the spec fired so each entry triggers
    exactly once even across prefetcher restarts and dispatch retries."""

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for raw in text.replace(",", ";").split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if "@" not in raw:
                raise ValueError(
                    f"fault-plan entry {raw!r} is not 'site@episode[:arg]'")
            site, _, rest = raw.partition("@")
            site = site.strip()
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (expected one of "
                    f"{', '.join(SITES)})")
            actor = None
            if site in ACTOR_KEYED:
                # a<actor>:<episode>[:arg] — the actor prefix is REQUIRED:
                # an actor-keyed fault with no actor would fire on whoever
                # reaches the episode first, making chaos runs racy
                if not rest.startswith("a"):
                    raise ValueError(
                        f"fault-plan entry {raw!r}: {site} is actor-keyed "
                        f"— use {site}@a<actor>:<episode>")
                actor_s, _, rest = rest[1:].partition(":")
                try:
                    actor = int(actor_s)
                except ValueError:
                    raise ValueError(
                        f"fault-plan entry {raw!r}: actor {actor_s!r} is "
                        "not an integer")
                if actor < 0:
                    raise ValueError(
                        f"fault-plan entry {raw!r}: actor must be >= 0")
                if not rest:
                    raise ValueError(
                        f"fault-plan entry {raw!r}: missing episode — use "
                        f"{site}@a<actor>:<episode>")
            elif site in VERSION_KEYED:
                if not rest.startswith("v"):
                    raise ValueError(
                        f"fault-plan entry {raw!r}: {site} is version-"
                        f"keyed — use {site}@v<version>")
                rest = rest[1:]
            ep_s, _, arg_s = rest.partition(":")
            try:
                episode = int(ep_s)
            except ValueError:
                what = ("version" if site in VERSION_KEYED else
                        "burst" if site in BURST_KEYED else "episode")
                raise ValueError(
                    f"fault-plan entry {raw!r}: {what} {ep_s!r} is not an "
                    "integer")
            if episode < 0:
                raise ValueError(
                    f"fault-plan entry {raw!r}: episode must be >= 0")
            arg = None
            if arg_s:
                try:
                    arg = float(arg_s)
                except ValueError:
                    raise ValueError(
                        f"fault-plan entry {raw!r}: arg {arg_s!r} is not a "
                        "number")
            specs.append(FaultSpec(site=site, episode=episode, arg=arg,
                                   actor=actor))
        if not specs:
            raise ValueError(f"empty fault plan {text!r}")
        return cls(specs)

    @classmethod
    def from_env(cls, flag: Optional[str] = None) -> Optional["FaultPlan"]:
        """Plan from an explicit flag value, falling back to the
        ``GSC_FAULT_PLAN`` environment variable only when no flag was
        given at all; None when neither is set.  An EXPLICIT empty flag
        (``--fault-plan ''``) disables injection even under an exported
        env plan — that is how an operator runs the clean control leg of
        a chaos comparison."""
        if flag is not None:
            text = flag.strip()
        else:
            text = os.environ.get(ENV_VAR, "").strip()
        return cls.parse(text) if text else None

    def fire(self, site: str, episode: int, actor: Optional[int] = None,
             at_or_after: bool = False) -> Optional[FaultSpec]:
        """The unfired spec for ``site`` keyed at ``episode`` (exact match,
        or the oldest spec with ``spec.episode <= episode`` when
        ``at_or_after`` — checkpoint saves only happen every interval, so
        an exact key could never land).  Actor-keyed specs additionally
        require ``actor`` to match, so ``actor_die@a0:3`` never fires on
        actor 1 even if it reaches episode 3 first.  Marks the spec
        fired."""
        with self._lock:
            for spec in self.specs:
                if spec.site != site or spec.fired:
                    continue
                if spec.actor is not None and spec.actor != actor:
                    continue
                if spec.episode == episode or (at_or_after
                                               and spec.episode <= episode):
                    spec.fired_at = episode
                    log.warning("fault injected: %s@%s (fired at key "
                                "%d, arg=%s)", site, spec.key, episode,
                                spec.arg)
                    return spec
        return None

    def summary(self) -> List[dict]:
        """JSON-able plan description (run_start meta / reports)."""
        with self._lock:
            return [{"site": s.site, "episode": s.episode, "arg": s.arg,
                     "actor": s.actor, "key": s.key, "fired": s.fired}
                    for s in self.specs]

    def unfired(self) -> List[FaultSpec]:
        """Specs that never triggered — a mis-keyed plan (e.g. an episode
        index past the run's end) should be loud, not silently green."""
        with self._lock:
            return [s for s in self.specs if not s.fired]

    def warn_unfired(self, hub=None) -> List[FaultSpec]:
        """End-of-run check shared by every training path (serial,
        replica-parallel, async): any entry that never fired gets a
        log.warning AND a structured ``fault_plan_unfired`` event on the
        hub, so a mis-keyed chaos plan cannot make a run look exercised
        while proving nothing.  Returns the unfired specs."""
        un = self.unfired()
        if un:
            keys = [f"{s.site}@{s.key}" for s in un]
            log.warning("fault plan entries never fired: %s", keys)
            if hub is not None:
                hub.event("fault_plan_unfired", entries=keys,
                          count=len(keys))
        return un
