"""Deterministic fault injection: the ``FaultPlan`` grammar and firing.

Production RL systems treat worker failure as normal operation (Podracer,
arXiv:2104.06272) and co-design the training loop with the platform's
failure modes (MindSpeed RL, arXiv:2507.19017) — but a recovery path that
has never executed is a recovery path that does not work.  A ``FaultPlan``
injects *named* faults at *named* sites keyed by episode index, so every
self-healing path in the trainer has a test (and a CI chaos stage) that
actually exercises it:

==================== =====================================================
site                 effect when the keyed episode is reached
==================== =====================================================
``prefetch_die``     the episode prefetcher's producer thread raises while
                     staging the keyed episode (surfaced on the consumer's
                     next ``get``; the trainer restarts the prefetcher)
``slow_episode``     the producer sleeps ``arg`` seconds (default 1.0)
                     before staging the keyed episode — long enough to trip
                     the watchdog, whose escalation interrupts/restarts the
                     prefetcher (the sleep aborts early on prefetcher stop)
``dispatch_transient`` episode dispatch raises a transient
                     ``XlaRuntimeError``-like failure once; the retry layer
                     backs off and re-dispatches
``nan_grads``        the learner state entering the keyed episode is
                     poisoned with NaN (the effect of a NaN gradient
                     update); the on-device all-finite guard detects it at
                     drain and the trainer rolls back
``ckpt_corrupt``     the first periodic checkpoint written at-or-after the
                     keyed episode is corrupted on disk; checksum
                     validation catches it and the manager re-saves
==================== =====================================================

Grammar (``--fault-plan`` / env ``GSC_FAULT_PLAN``)::

    plan  := entry (";" entry)*
    entry := site "@" episode [":" arg]

e.g. ``prefetch_die@1;nan_grads@3;slow_episode@2:1.5``.  Each entry fires
exactly ONCE (thread-safe), which is what makes the recovery paths
convergent: a restarted prefetcher re-staging the same episode does not
re-hit the fault.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import List, Optional

log = logging.getLogger("gsc_tpu.resilience.faults")

SITES = ("prefetch_die", "slow_episode", "dispatch_transient", "nan_grads",
         "ckpt_corrupt")

ENV_VAR = "GSC_FAULT_PLAN"


class FaultInjected(RuntimeError):
    """An injected (non-transient) fault — e.g. the prefetcher producer's
    death.  Distinct from the transient class so the retry layer never
    retries a fault that models a hard failure."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    episode: int
    arg: Optional[float] = None
    fired_at: Optional[int] = None   # episode the fault actually fired at

    @property
    def fired(self) -> bool:
        return self.fired_at is not None


class FaultPlan:
    """Parsed fault schedule; ``fire`` is the single (locked) gate every
    injection site calls — marking the spec fired so each entry triggers
    exactly once even across prefetcher restarts and dispatch retries."""

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for raw in text.replace(",", ";").split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if "@" not in raw:
                raise ValueError(
                    f"fault-plan entry {raw!r} is not 'site@episode[:arg]'")
            site, _, rest = raw.partition("@")
            site = site.strip()
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (expected one of "
                    f"{', '.join(SITES)})")
            ep_s, _, arg_s = rest.partition(":")
            try:
                episode = int(ep_s)
            except ValueError:
                raise ValueError(
                    f"fault-plan entry {raw!r}: episode {ep_s!r} is not an "
                    "integer")
            if episode < 0:
                raise ValueError(
                    f"fault-plan entry {raw!r}: episode must be >= 0")
            arg = None
            if arg_s:
                try:
                    arg = float(arg_s)
                except ValueError:
                    raise ValueError(
                        f"fault-plan entry {raw!r}: arg {arg_s!r} is not a "
                        "number")
            specs.append(FaultSpec(site=site, episode=episode, arg=arg))
        if not specs:
            raise ValueError(f"empty fault plan {text!r}")
        return cls(specs)

    @classmethod
    def from_env(cls, flag: Optional[str] = None) -> Optional["FaultPlan"]:
        """Plan from an explicit flag value, falling back to the
        ``GSC_FAULT_PLAN`` environment variable only when no flag was
        given at all; None when neither is set.  An EXPLICIT empty flag
        (``--fault-plan ''``) disables injection even under an exported
        env plan — that is how an operator runs the clean control leg of
        a chaos comparison."""
        if flag is not None:
            text = flag.strip()
        else:
            text = os.environ.get(ENV_VAR, "").strip()
        return cls.parse(text) if text else None

    def fire(self, site: str, episode: int,
             at_or_after: bool = False) -> Optional[FaultSpec]:
        """The unfired spec for ``site`` keyed at ``episode`` (exact match,
        or the oldest spec with ``spec.episode <= episode`` when
        ``at_or_after`` — checkpoint saves only happen every interval, so
        an exact key could never land).  Marks the spec fired."""
        with self._lock:
            for spec in self.specs:
                if spec.site != site or spec.fired:
                    continue
                if spec.episode == episode or (at_or_after
                                               and spec.episode <= episode):
                    spec.fired_at = episode
                    log.warning("fault injected: %s@%d (fired at episode "
                                "%d, arg=%s)", site, spec.episode, episode,
                                spec.arg)
                    return spec
        return None

    def summary(self) -> List[dict]:
        """JSON-able plan description (run_start meta / reports)."""
        with self._lock:
            return [{"site": s.site, "episode": s.episode, "arg": s.arg,
                     "fired": s.fired} for s in self.specs]

    def unfired(self) -> List[FaultSpec]:
        """Specs that never triggered — a mis-keyed plan (e.g. an episode
        index past the run's end) should be loud, not silently green."""
        with self._lock:
            return [s for s in self.specs if not s.fired]
