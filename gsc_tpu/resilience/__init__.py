"""Self-healing training: fault injection, guards, retry, preemption.

PR 2's watchdog and PR 4's sentinels made trouble *visible*; this package
makes the stack *survive* it, and proves each path with injected faults:

- :mod:`~gsc_tpu.resilience.faults` — ``FaultPlan``: deterministic named
  faults at named sites keyed by episode index
  (``--fault-plan`` / ``GSC_FAULT_PLAN``).
- :mod:`~gsc_tpu.resilience.guard` — on-device all-finite flags folded
  into the fused episode programs + the trainer's last-good rollback
  snapshot.
- :mod:`~gsc_tpu.resilience.retry` — bounded exponential backoff around
  episode dispatch for transient ``XlaRuntimeError``-like failures.
- :mod:`~gsc_tpu.resilience.preempt` — SIGTERM/SIGINT ->
  snapshot-and-exit-cleanly.
- :mod:`~gsc_tpu.resilience.ckpt` — checksummed periodic checkpoints with
  a rotating last-good pointer and ``--resume auto`` discovery.  (Import
  the submodule directly: it pulls in the checkpoint/agent stack, which
  would make this package's import circular for ``agents.ddpg``'s use of
  :func:`~gsc_tpu.resilience.guard.all_finite`.)

The degradation ladder, every rung reported as a structured ``recovery``
event in ``events.jsonl``:

    retry (dispatch) -> prefetcher restart -> pipeline off -> rollback
"""
from .faults import ENV_VAR, SITES, FaultInjected, FaultPlan, FaultSpec
from .guard import RollbackGuard, all_finite, poison_tree, tree_copy
from .preempt import PreemptionGuard
from .retry import (RetryPolicy, TransientDispatchError, call_with_retry,
                    transient_error_types)

__all__ = [
    "ENV_VAR", "SITES", "FaultInjected", "FaultPlan", "FaultSpec",
    "RollbackGuard", "all_finite", "poison_tree", "tree_copy",
    "PreemptionGuard", "RetryPolicy", "TransientDispatchError",
    "call_with_retry", "transient_error_types",
]
