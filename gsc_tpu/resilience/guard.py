"""Divergence guardrails: on-device all-finite flags + rollback snapshots.

One NaN gradient poisons the learner state forever — every later episode's
actions, replay writes and updates inherit it, and the run quietly trains
garbage until someone reads the loss curve.  The guard is two pieces:

- :func:`all_finite` — a scalar flag over a pytree's inexact leaves,
  computed ON DEVICE inside the fused ``episode_step``/``chunk_step``
  programs (``DDPG._rollout_body`` flags the state entering the episode,
  ``_learn_burst`` flags the post-update state) and drained with the
  existing deferred metrics — zero extra host syncs.
- :class:`RollbackGuard` — the trainer's last-good in-memory snapshot.
  Because the pipelined loop dispatches episode k+1 before episode k's
  metrics (and its finite flag) drain, the snapshot taken at a dispatch
  boundary is *unverified*; the guard stages it as a candidate and only
  promotes it to ``last_good`` once the matching episode drains finite.
  On a violation the trainer restores ``last_good`` (always a verified
  state), drops the in-flight episode, and continues.

Cost: two device-side pytree copies per episode (learner state + replay
buffer) and one retained copy of each — ~2 extra replay-buffer residents
in HBM.  ``Trainer(rollback=False)`` disables the snapshots (the flag is
still computed and surfaced).
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp


def all_finite(tree: Any) -> jnp.ndarray:
    """Scalar f32 flag (1.0/0.0): every inexact leaf of ``tree`` is
    finite.  Pure jnp — safe to trace inside the fused episode programs;
    integer leaves (PRNG keys, ring-buffer counters) are skipped."""
    flags = [jnp.isfinite(leaf).all()
             for leaf in jax.tree_util.tree_leaves(tree)
             if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]
    if not flags:
        return jnp.float32(1.0)
    return jnp.stack(flags).all().astype(jnp.float32)


def tree_copy(tree: Any) -> Any:
    """Device-side copy of every array leaf — snapshots must not alias
    buffers that the next dispatch donates."""
    return jax.tree_util.tree_map(jnp.copy, tree)


def poison_tree(tree: Any) -> Any:
    """NaN every inexact leaf (the ``nan_grads`` fault: the effect of a
    NaN gradient update on the learner state)."""
    return jax.tree_util.tree_map(
        lambda x: x * jnp.asarray(float("nan"), jnp.asarray(x).dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact) else x, tree)


class RollbackGuard:
    """Last-good (state, buffer) snapshot with deferred-verification
    promotion — see the module docstring for why a candidate stage is
    needed under the asynchronous pipeline."""

    def __init__(self):
        # (episode_tag, state, buffer): "state after all episodes <= tag"
        self.last_good: Optional[Tuple[int, Any, Any]] = None
        self._candidate: Optional[Tuple[int, Any, Any]] = None
        self.rollbacks = 0

    def init(self, episode_tag: int, state, buffer):
        """Seed ``last_good`` with the (trivially finite) initial state so
        a violation on the very first episode still has a rollback
        target."""
        self.last_good = (episode_tag, tree_copy(state), tree_copy(buffer))

    def stage(self, episode_tag: int, state, buffer):
        """Candidate snapshot at a dispatch boundary (state after episode
        ``episode_tag``, not yet drained/verified).  Called BEFORE any
        fault injection and before the dispatch donates the carries."""
        self._candidate = (episode_tag, tree_copy(state), tree_copy(buffer))

    def promote(self, drained_episode: int, state, buffer,
                pending_empty: bool):
        """Episode ``drained_episode`` drained with a finite flag: promote
        the matching candidate to ``last_good``.  When nothing is in
        flight (serial loop, or the pipeline's tail drain) the live
        carries ARE the verified state — snapshot them directly, which
        also advances past the one-episode candidate lag."""
        c = self._candidate
        if c is not None and c[0] == drained_episode:
            self.last_good = c
            self._candidate = None
        elif pending_empty:
            self.last_good = (drained_episode, tree_copy(state),
                              tree_copy(buffer))
            self._candidate = None

    def restore(self) -> Tuple[int, Any, Any]:
        """Copies of ``last_good`` (the retained snapshot must survive a
        later rollback, and the returned carries will be donated)."""
        self.rollbacks += 1
        self._candidate = None   # descendant of the poisoned state
        tag, state, buffer = self.last_good
        return tag, tree_copy(state), tree_copy(buffer)
