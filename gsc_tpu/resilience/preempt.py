"""Preemption guard: SIGTERM/SIGINT -> snapshot-and-exit-cleanly.

Preemptible capacity (spot VMs, borrowed TPU slices) delivers SIGTERM with
a grace window; the default Python behavior — die mid-episode, losing
everything since the last manual checkpoint — wastes the window.  The
guard converts the first signal into a flag the training loop polls at
episode boundaries: the trainer finishes draining what's in flight, the
CLI writes a checksummed checkpoint and exits 0, and ``--resume auto``
picks the run back up with a monotone episode counter.

A SECOND signal restores the original handlers, so a stuck teardown can
still be killed the ordinary way.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

log = logging.getLogger("gsc_tpu.resilience.preempt")

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionGuard:
    """Context manager installing graceful-shutdown handlers.

    Must be entered from the main thread (CPython restricts
    ``signal.signal``); anywhere else it degrades to an inert flag that
    never triggers, logging why."""

    def __init__(self, signals=_DEFAULT_SIGNALS):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._previous = {}
        self.signum: Optional[int] = None

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def signame(self) -> Optional[str]:
        if self.signum is None:
            return None
        try:
            return signal.Signals(self.signum).name
        except ValueError:
            return str(self.signum)

    def _handle(self, signum, frame):
        if self._event.is_set():
            # second signal: the operator means it — restore the original
            # disposition so the NEXT one terminates the process
            log.warning("second %s during graceful shutdown — restoring "
                        "default handlers", self.signame)
            self._restore()
            return
        self.signum = signum
        self._event.set()
        log.warning("received %s — will snapshot a checkpoint at the next "
                    "episode boundary and exit cleanly", self.signame)

    def __enter__(self) -> "PreemptionGuard":
        try:
            for sig in self.signals:
                self._previous[sig] = signal.signal(sig, self._handle)
        except ValueError as e:   # not the main thread
            log.warning("preemption guard inactive (%s) — signals keep "
                        "their default disposition", e)
            self._restore()
        return self

    def _restore(self):
        for sig, prev in list(self._previous.items()):
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
            self._previous.pop(sig, None)

    def __exit__(self, *exc):
        self._restore()
        return False
