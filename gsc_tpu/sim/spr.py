"""Shortest-path-routing (SPR) per-flow heuristic — the example algorithm
the reference's per-flow control granularity exists for.

The reference's ``FlowController`` hands each waiting flow to an external
algorithm as an ``SPRState`` (flow + network view + stats,
coordsim/controller/flow_controller.py:10-18) and applies the returned
destination node (flow_controller.py:44-92).  No concrete algorithm ships
inside the reference tree — this module provides the canonical one the API
is named after: process at the nearest capable node, routing over shortest
paths.

Decision rule per waiting flow (given ``PendingFlows``):

1. If the current node can host the flow's next SF — the SF is already
   available there (or could be placed, when ``place_on_decision``) and the
   node has ``dr`` worth of remaining capacity — process HERE
   (destination = current node; the engine's place-on-decision installs the
   SF if absent, engine.py ext_decisions path).
2. Otherwise pick the node with remaining capacity that minimizes shortest-
   path delay from the current node, preferring nodes where the SF is
   already running (no startup delay, no placement churn); unreachable
   nodes (infinite path delay) and nodes whose path exceeds the flow's TTL
   are excluded.
3. If no node qualifies, stay put — the engine then attempts processing at
   the current node and records the authentic NODE_CAP drop
   (base_processor.py:51-101 semantics), matching what the reference's
   simulator does to an algorithm with nowhere to send a flow.

Host-side numpy on the ``PendingFlows`` network view: this is the external
(non-JAX) algorithm path; the on-device analogue is
``SimEngine.apply_per_flow`` with a jitted policy.
"""
from __future__ import annotations

import numpy as np

from .perflow import PendingFlows, PerFlowController
from .state import SimState


class ShortestPathAlgo:
    """Greedy nearest-capable-node per-flow algorithm (see module doc).

    ``prefer_running=True`` breaks delay ties toward nodes where the needed
    SF is already available, and only falls back to empty nodes when no
    running instance is reachable."""

    def __init__(self, prefer_running: bool = True):
        self.prefer_running = prefer_running

    def decide(self, pending: PendingFlows) -> np.ndarray:
        """[K] destination node per pending flow (>=0 always: rule 3 keeps
        undecidable flows at their current node rather than parking them
        forever with -1)."""
        from ..topology.compiler import INF_DELAY

        k = len(pending)
        out = np.empty(k, np.int32)
        # working copy: decisions in one batch land in the SAME substep, so
        # each routed flow must reserve its dr or two flows could jointly
        # overload a node the sequential reference algorithm would not
        node_rem = pending.node_remaining.copy()
        avail = pending.sf_available
        pd = pending.path_delay
        for i in range(k):
            cur = int(pending.node[i])
            sf = int(pending.sf[i])
            dr = float(pending.dr[i])
            fits = node_rem >= dr
            if fits[cur]:
                out[i] = cur
                node_rem[cur] -= dr
                continue
            # pad/unreachable pairs carry the finite INF_DELAY sentinel,
            # not inf (compiler.py) — compare against it, not isfinite
            reach = (pd[cur] < INF_DELAY) & (pd[cur] <= pending.ttl[i])
            cand = fits & reach
            if self.prefer_running and (cand & avail[:, sf]).any():
                cand = cand & avail[:, sf]
            if cand.any():
                delays = np.where(cand, pd[cur], np.inf)
                out[i] = int(np.argmin(delays))
                node_rem[out[i]] -= dr
            else:
                out[i] = cur  # rule 3: authentic NODE_CAP drop
        return out


def run_spr_episode(controller: PerFlowController, state: SimState,
                    num_substeps: int, algo: ShortestPathAlgo = None
                    ) -> SimState:
    """Drive ``PerFlowController`` with ``ShortestPathAlgo`` for
    ``num_substeps`` engine substeps — the end-to-end per-flow control loop
    a reference user writes against FlowController.get_init_state /
    get_next_state (flow_controller.py:30-92)."""
    algo = algo or ShortestPathAlgo()
    dt = controller.engine.dt

    def substeps(st):
        return int(round(float(st.t) / dt))

    while substeps(state) < num_substeps:
        state, pending = controller.run_until_decision(
            state, max_substeps=num_substeps - substeps(state))
        if not len(pending):
            break  # budget ran out with nothing waiting
        if substeps(state) < num_substeps:
            state = controller.decide(state, pending, algo.decide(pending))
        else:
            break
    return state
