"""Batched fixed-step flow simulation engine.

The functional replacement for the reference's SimPy discrete-event core
(coordsim/simulation/flowsimulator.py + forwarders/processors/decision_maker).
One *control interval* (= one RL step, ``run_duration`` ms) is a ``lax.scan``
over ``run_duration/dt`` fixed substeps; each substep advances every flow slot
in the preallocated ``FlowTable`` in parallel.  There is no data-dependent
Python control flow — the whole episode jits, vmaps over env replicas, and
shards over device meshes.

Per-substep pipeline (mirroring the reference's per-flow state machine,
flowsimulator.py:72-128):
 1. release capacities whose hold time elapsed (ring buffers; the analogue of
    the delayed ``return_link_resources`` / ``finish_processing`` SimPy
    processes, default_forwarder.py:112-125, base_processor.py:103-135)
 2. advance HOP/PROC timers; completed PROC flows advance their SFC position
    (base_processor.py:104-107) and re-enter decision; completed hops either
    continue the path, arrive for processing, or depart at egress
 3. admit new arrivals from the pre-generated TrafficSchedule into free slots
 4. decisions: egress routing for finished flows (default_decision_maker.py:
    27-31) and weighted-round-robin next-node selection against the
    scheduling table with per-(node,SFC,SF) realized-ratio counters
    (default_decision_maker.py:42-66); same-substep collisions in one cell
    are serialized over ``wrr_rank_levels`` rounds
 5. forwarding: upfront whole-path TTL check (default_forwarder.py:35-39),
    then hop-by-hop traversal with per-edge capacity admission
    (default_forwarder.py:95-111); same-substep contention on an edge is
    resolved greedily in slot order via iterative prefix-sum refinement
 6. processing: SF-placement check (default_processor.py:30-50), processing
    delay sampling |N(mean, stdev)| with TTL check (base_processor.py:37-49),
    node capacity admission through per-SF resource functions
    (base_processor.py:24-35, 51-101), startup-delay wait, delayed load
    release after the flow duration
 7. departures and drops with the reference's 4-reason taxonomy
    (metrics.py:144-164; a drop with TTL<=0 is always recorded as TTL)

Known, documented divergences from the event-driven reference:
- time is quantized to ``dt`` (default 1 ms — exact for the default integer-
  delay configs); sampled delays are credited to metrics exactly, only state
  transitions snap to substep boundaries
- same-instant orderings inside one substep follow flow-slot order instead of
  SimPy's FIFO queue order
- same-substep capacity contention uses ``admission_iters`` refinement
  rounds, which equals greedy slot-order admission except in pathological
  cascades
- a flow whose TTL expires during a VNF startup wait releases its node load
  (the reference leaks it, base_processor.py:86-97)
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.registry import get_resource_function
from ..config.schema import EnvLimits, ServiceConfig, SimConfig
from ..topology.compiler import Topology
from .state import (
    DROP_DECISION,
    DROP_LINK_CAP,
    DROP_NODE_CAP,
    DROP_TTL,
    PH_DECIDE,
    PH_FREE,
    PH_HOP,
    PH_PROC,
    FlowTable,
    SimMetrics,
    SimState,
    TrafficSchedule,
    init_state,
)

_EPS = 1e-4
# arrivals admitted per substep; later arrivals spill to the next substep
# (with default dt=1ms this is never binding outside extreme overload)
_ARRIVALS_PER_SUBSTEP = 8


@dataclass(frozen=True)
class ServiceTables:
    """Static per-service tensors derived from ServiceConfig."""

    chain_sf: np.ndarray      # [C, S_pos] i32 SF id per chain position (-1 pad)
    chain_len: np.ndarray     # [C] i32
    proc_mean: np.ndarray     # [P] f32, P = size of the SF catalog
    proc_std: np.ndarray      # [P] f32
    startup_delay: np.ndarray  # [P] f32
    resource_fns: Tuple[Callable, ...]  # per SF id

    @classmethod
    def build(cls, service: ServiceConfig, limits: EnvLimits) -> "ServiceTables":
        sf_names = list(service.sf_names)
        s = limits.max_sfs
        c = limits.num_sfcs
        pool = limits.sf_pool
        if len(sf_names) > pool:
            raise ValueError(
                f"SF catalog has {len(sf_names)} SFs but limits.sf_pool is "
                f"{pool}; set EnvLimits.num_sfs (EnvLimits.for_service does)")
        chain_sf = np.full((c, s), -1, np.int32)
        chain_len = np.zeros(c, np.int32)
        for ci, name in enumerate(service.sfc_names):
            chain = service.sfc_list[name]
            chain_len[ci] = len(chain)
            for si, sf in enumerate(chain):
                chain_sf[ci, si] = sf_names.index(sf)
        proc_mean = np.zeros(pool, np.float32)
        proc_std = np.zeros(pool, np.float32)
        startup = np.zeros(pool, np.float32)
        fns = []
        for i, name in enumerate(sf_names[:pool]):
            sf = service.sf_list[name]
            proc_mean[i] = sf.processing_delay_mean
            proc_std[i] = sf.processing_delay_stdev
            startup[i] = sf.startup_delay
            fns.append(get_resource_function(sf.resource_function_id))
        while len(fns) < pool:
            fns.append(get_resource_function("default"))
        return cls(chain_sf=chain_sf, chain_len=chain_len, proc_mean=proc_mean,
                   proc_std=proc_std, startup_delay=startup,
                   resource_fns=tuple(fns))


_HI = jax.lax.Precision.HIGHEST


def _onehot(idx: jnp.ndarray, n: int, dtype=jnp.float32) -> jnp.ndarray:
    """[M] i32 -> [M, n] one-hot rows; out-of-range indices give all-zero
    rows (the ``mode="drop"`` analogue).

    TPU rationale: vmapped gathers/scatters lower to per-index serial
    updates (~2 ns/element, linear in B*M — measured to dominate the
    substep at B>=256), while one-hot contractions run on the MXU/VPU.
    With ``Precision.HIGHEST`` a one-hot dot is EXACT: each output is a
    single 1.0*x product (bf16x3 splits a f32 mantissa exactly; all other
    terms are 0), so gather/scatter semantics are reproduced bit-for-bit
    up to f32 summation order in the scatter-add cases."""
    return (idx[:, None] == jnp.arange(n, dtype=idx.dtype)[None, :]
            ).astype(dtype)


def _take(table: jnp.ndarray, oh: jnp.ndarray) -> jnp.ndarray:
    """rows ``table[idx]`` via a precomputed one-hot [M, n] @ [n, ...]."""
    t = table.astype(jnp.float32)
    flat = t.reshape(t.shape[0], -1)
    out = jnp.dot(oh, flat, precision=_HI).reshape((oh.shape[0],) + t.shape[1:])
    if table.dtype == jnp.bool_:
        return out > 0.5
    if jnp.issubdtype(table.dtype, jnp.integer):
        return jnp.round(out).astype(table.dtype)
    return out


def _pick(rows: jnp.ndarray, oh_col: jnp.ndarray) -> jnp.ndarray:
    """rows[m, idx[m]] for per-row column indices as a masked VPU reduce:
    [M, n] rows x [M, n] one-hot -> [M]."""
    out = (rows.astype(jnp.float32) * oh_col).sum(-1)
    if rows.dtype == jnp.bool_:
        return out > 0.5
    if jnp.issubdtype(rows.dtype, jnp.integer):
        return jnp.round(out).astype(rows.dtype)
    return out


def _group_order(cell_id: jnp.ndarray) -> jnp.ndarray:
    """Permutation sorting flows by (cell, slot) — groups each cell's flows
    contiguously in slot order.  Keys are made unique with the slot index,
    so no stability assumption is needed.  Division of labor on TPU: the
    SORT does the grouping (vectorized bitonic network), while all data
    movement along the resulting permutation runs as [M, M] one-hot dots
    (see ``_onehot``) — deliberately O(M^2) MXU work per substep, which
    beats the serial per-index gathers/scatters it replaces by ~8x on the
    measured chip."""
    m = cell_id.shape[0]
    return jnp.argsort(cell_id * m + jnp.arange(m))


def _run_starts(sorted_cell: jnp.ndarray) -> jnp.ndarray:
    """For each sorted position, the first position of its cell's run."""
    idx = jnp.arange(sorted_cell.shape[0])
    new = jnp.concatenate([jnp.ones((1,), bool),
                           sorted_cell[1:] != sorted_cell[:-1]])
    return jax.lax.cummax(jnp.where(new, idx, 0))


def _rank_in_cell(cell_id: jnp.ndarray, mask: jnp.ndarray,
                  num_cells: int) -> jnp.ndarray:
    """rank[m] = #(flows m'<m with mask and same cell).  [M] i32.
    Only meaningful under ``mask`` (masked-out flows rank in a sentinel
    cell).  Permutation gathers/scatters run as one-hot dots (see
    ``_onehot``)."""
    m = cell_id.shape[0]
    key = jnp.where(mask, cell_id, num_cells)
    order = _group_order(key)
    perm = _onehot(order, m)
    key_sorted = jnp.round(jnp.dot(perm, key.astype(jnp.float32),
                                   precision=_HI)).astype(key.dtype)
    starts = _run_starts(key_sorted)
    rank_sorted = (jnp.arange(m) - starts).astype(jnp.float32)
    return jnp.round(jnp.dot(rank_sorted, perm, precision=_HI)
                     ).astype(jnp.int32)


class SimEngine:
    """Factory-built engine closing over static config.

    ``init(rng, topo)`` -> SimState (the analogue of SimulatorInterface.init,
    spinterface.py:199-218, without running any events — matching the
    reference's init which only executes the t=0 bookkeeping event,
    duration_controller.py:20-33).

    ``apply(state, topo, traffic, schedule, placement)`` -> (state', metrics)
    runs one control interval (SimulatorInterface.apply / DurationController.
    get_next_state, duration_controller.py:35-77).
    """

    def __init__(self, service: ServiceConfig, cfg: SimConfig, limits: EnvLimits):
        self.service = service
        self.cfg = cfg
        self.limits = limits
        self.tables = ServiceTables.build(service, limits)
        self.substeps = cfg.substeps_per_run
        self.dt = cfg.dt
        self.M = cfg.max_flows
        self.H = cfg.release_horizon
        self.N = limits.max_nodes
        self.C = limits.num_sfcs
        self.S = limits.max_sfs     # chain-position axis (schedule tensor)
        self.P = limits.sf_pool     # SF-id axis (placement/load/proc tables)
        self.E = limits.max_edges
        max_hold = (self.H - 1) * self.dt
        if cfg.run_duration > max_hold:
            raise ValueError("release_horizon must cover at least one run_duration")
        # static deterministic-processing-delay flag, shared by both
        # substep impls (the pallas path draws its noise OUTSIDE the
        # kernel with the same key, so the rng stream is impl-invariant)
        self._det_proc = float(np.max(self.tables.proc_std)) == 0.0

    # ------------------------------------------------------------------ init
    def init(self, rng, topo: Topology) -> SimState:
        del topo  # shapes are static; topology enters at apply()
        return init_state(rng, self.M, self.N, self.C, self.S, self.E,
                          self.H, p=self.P)

    # ------------------------------------------------------- demanded capacity
    def _demanded(self, load_plus: jnp.ndarray, avail: jnp.ndarray) -> jnp.ndarray:
        """Total demanded node capacity given per-SF loads [..., P] summed over
        available SFs through per-SF resource functions
        (base_processor.py:24-35)."""
        cols = []
        for s, fn in enumerate(self.tables.resource_fns):
            cols.append(jnp.where(avail[..., s], fn(load_plus[..., s]), 0.0))
        return jnp.stack(cols, axis=-1).sum(axis=-1)

    # ------------------------------------------------------------- one interval
    @partial(jax.jit, static_argnums=0)
    def apply(self, state: SimState, topo: Topology, traffic: TrafficSchedule,
              schedule: jnp.ndarray, placement: jnp.ndarray
              ) -> Tuple[SimState, SimMetrics]:
        # --- apply the action (duration_controller.py:44-64) ---
        available = placement | (state.node_load > _EPS)
        newly = available & ~state.sf_available
        state = state.replace(
            placed=placement,
            schedule=schedule,
            sf_available=available,
            sf_startup=jnp.where(newly, state.t, state.sf_startup),
            # fresh instances start their idle clock now ('last_active':
            # env.now at creation, duration_controller.py:55-59)
            sf_last_active=jnp.where(newly, state.t, state.sf_last_active),
            # run metrics reset at interval start (writer.py:222-225)
            metrics=state.metrics.reset_run(),
        )
        t_steps = traffic.node_cap.shape[0]
        idx_now = jnp.clip(state.run_idx, 0, t_steps - 1)
        cap_now = traffic.node_cap[idx_now]
        # link-fault scenarios (topology.scenarios): when the schedule
        # carries a per-interval edge-capacity table, this interval's row
        # REPLACES the static edge caps for every substep below — the
        # structural check is trace-time (None = the historic program,
        # byte for byte), the row select is device work
        if traffic.edge_cap_t is not None:
            topo = topo.replace(edge_cap=traffic.edge_cap_t[idx_now])

        def sub(st, _):
            return self._substep(st, topo, traffic, cap_now), None

        # unroll trades compile time for per-iteration scan overhead — the
        # substep is a chain of small fusions, so on TPU the loop machinery
        # is a visible fraction of the wall (cfg.scan_unroll, default 1)
        state, _ = jax.lax.scan(sub, state, None, length=self.substeps,
                                unroll=self.cfg.scan_unroll)
        state = state.replace(run_idx=state.run_idx + 1)
        return state, state.metrics

    # ------------------------------------------------------ per-flow control
    @partial(jax.jit, static_argnums=0)
    def apply_substep(self, state: SimState, topo: Topology,
                      traffic: TrafficSchedule,
                      ext_decisions: jnp.ndarray) -> SimState:
        """One substep under *per-flow* control (the reference's
        FlowController / ExternalDecisionMaker granularity,
        coordsim/controller/flow_controller.py:21-92).

        ``ext_decisions`` [M] i32: destination node for each flow slot, or -1
        to leave the flow waiting.  Flows at a decision point without a
        decision stay parked in the DECIDE phase (the analogue of blocking on
        ``flow_trigger``, external_decision_maker.py:45-53); the chosen SF is
        placed on the decided node if absent (place-on-decision,
        flow_controller.py:46-60).  ``run_idx`` tracks wall sim-time so
        trace-driven caps/activity stay aligned; run metrics reset at the
        *start* of each new interval (writer.py:222-225), so after an
        interval's final substep its run counters remain readable."""
        # integer substep counter (round() absorbs float32 drift in t)
        g = jnp.round(state.t / self.dt).astype(jnp.int32)
        new_idx = g // self.substeps
        starts_interval = (g % self.substeps == 0) & (g > 0)
        metrics = jax.tree_util.tree_map(
            lambda a, b: jnp.where(starts_interval, a, b),
            state.metrics.reset_run(), state.metrics)
        state = state.replace(run_idx=jnp.maximum(new_idx, state.run_idx),
                              metrics=metrics)
        t_steps = traffic.node_cap.shape[0]
        idx = jnp.clip(state.run_idx, 0, t_steps - 1)
        cap_now = traffic.node_cap[idx]
        if traffic.edge_cap_t is not None:
            # same link-fault row select as apply() — per-flow control
            # sees the identical capacity timeline
            topo = topo.replace(edge_cap=traffic.edge_cap_t[idx])
        return self._substep(state, topo, traffic, cap_now,
                             ext_decisions=ext_decisions)

    def apply_per_flow(self, state: SimState, topo: Topology,
                       traffic: TrafficSchedule, decide_fn
                       ) -> Tuple[SimState, SimMetrics]:
        """One control interval with a *jitted* per-flow policy:
        ``decide_fn(state) -> [M] i32`` (-1 = no decision) is invoked every
        substep — the TPU-native form of the per-flow control loop, keeping
        the whole interval on device."""
        def sub(st, _):
            return self.apply_substep(st, topo, traffic, decide_fn(st)), None

        state, _ = jax.lax.scan(sub, state, None, length=self.substeps)
        return state, state.metrics

    # ---------------------------------------------------------------- substep
    def _substep(self, state: SimState, topo: Topology,
                 traffic: TrafficSchedule, cap_now: jnp.ndarray,
                 ext_decisions: jnp.ndarray | None = None) -> SimState:
        """Dispatch on ``cfg.substep_impl``: "xla" = the hand-fused
        one-hot pipeline below; "pallas" = the substep megakernel (ONE
        pallas_call per substep, ops/pallas_substep.py — bit-exact vs
        the XLA body, asserted by ``pytest -m megakernel``).  Per-flow
        external decisions always run the XLA body (SimConfig rejects
        the pallas impl for controller="per_flow")."""
        if self.cfg.substep_impl == "pallas" and ext_decisions is None:
            return self._substep_pallas(state, topo, traffic, cap_now)
        return self._substep_xla(state, topo, traffic, cap_now,
                                 ext_decisions)

    def _substep_pallas(self, state: SimState, topo: Topology,
                        traffic: TrafficSchedule,
                        cap_now: jnp.ndarray) -> SimState:
        """Megakernel path: advance the rng stream EXACTLY as the XLA
        body does (split; stochastic configs draw the [M] processing-
        delay normals from the same k_proc), then run the whole substep
        as one kernel invocation."""
        # lazy import: the kernel module reuses this module's one-hot
        # helpers, so the dependency edge must point pallas_substep ->
        # engine (resolved once at first trace, never per step)
        from ..ops.pallas_substep import substep_megakernel

        rng, k_proc = jax.random.split(state.rng)
        if self._det_proc:
            noise = jnp.zeros((self.M,), jnp.float32)
        else:
            noise = jax.random.normal(k_proc, (self.M,))
        state = state.replace(rng=rng)
        return substep_megakernel(state, topo, traffic, cap_now, noise,
                                  tables=self.tables, cfg=self.cfg,
                                  limits=self.limits, det=self._det_proc)

    def _substep_xla(self, state: SimState, topo: Topology,
                     traffic: TrafficSchedule, cap_now: jnp.ndarray,
                     ext_decisions: jnp.ndarray | None = None) -> SimState:
        F = state.flows
        m = state.metrics
        dt = self.dt
        t = state.t
        g = jnp.round(t / dt).astype(jnp.int32)       # global substep index
        ridx = jnp.mod(g, self.H)                      # ring-buffer index
        slots = jnp.arange(self.M)
        rng, k_proc = jax.random.split(state.rng)

        # --- 1. capacity releases ------------------------------------------
        node_load = jnp.maximum(
            state.node_load - state.rel_node[ridx].reshape(self.N, self.P),
            0.0)
        edge_used = jnp.maximum(state.edge_used - state.rel_edge[ridx], 0.0)
        rel_node = state.rel_node.at[ridx].set(0.0)
        rel_edge = state.rel_edge.at[ridx].set(0.0)
        # graceful SF removal once drained and unplaced (base_processor.py:115-118)
        sf_available = state.sf_available & (state.placed | (node_load > _EPS))

        # --- 2. timers ------------------------------------------------------
        running = (F.phase == PH_HOP) | (F.phase == PH_PROC)
        timer = jnp.where(running, F.timer - dt, F.timer)
        proc_done = (F.phase == PH_PROC) & (timer <= _EPS)
        hop_done = (F.phase == PH_HOP) & (timer <= _EPS)

        # PROC completion: advance chain position, re-decide this substep
        # (position increments when processing delay elapses,
        # base_processor.py:103-107 at spawn time)
        position = F.position + proc_done.astype(jnp.int32)
        phase = jnp.where(proc_done, PH_DECIDE, F.phase)

        # HOP completion: move to hop endpoint
        node = jnp.where(hop_done, F.hop_next, F.node)
        arrived = hop_done & (node == F.dest)
        cont = hop_done & ~arrived                     # continue multi-hop path
        # credit whole-path delay on arrival (default_forwarder.py:83-86)
        e2e = F.e2e + jnp.where(arrived, F.pend_path, 0.0)
        ttl = F.ttl - jnp.where(arrived, F.pend_path, 0.0)
        n_arr = arrived.sum()
        path_add = jnp.where(arrived, F.pend_path, 0.0).sum()
        m = m.replace(
            sum_path_delay=m.sum_path_delay + path_add,
            num_path_delay=m.num_path_delay + n_arr,
            run_path_delay_sum=m.run_path_delay_sum + path_add,
        )
        # un-clipped one-hot: an out-of-range SFC id gives an all-zero row
        # (chain_len = 0), so a corrupt-sfc flow heads to egress instead of
        # being silently attributed to chain C-1; stage 4 reads chain_len
        # the same way so the two lookups agree on the flow's chain
        chain_len = _take(jnp.asarray(self.tables.chain_len),
                          _onehot(F.sfc, self.C))
        to_eg_flag = position >= chain_len             # forward_to_eg
        depart_hop = arrived & to_eg_flag              # reached egress: success
        need_proc_a = arrived & ~to_eg_flag

        # --- 3. arrivals ----------------------------------------------------
        cand = state.cursor + jnp.arange(_ARRIVALS_PER_SUBSTEP)
        cand_c = jnp.clip(cand, 0, traffic.capacity - 1)
        due = (traffic.arr_time[cand_c] < t + dt - _EPS) & (cand < traffic.capacity) \
            & jnp.isfinite(traffic.arr_time[cand_c])
        free = phase == PH_FREE
        free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
        n_free = free.sum()
        arr_rank = jnp.cumsum(due.astype(jnp.int32)) - 1
        spawn = due & (arr_rank < n_free)
        # slot_of_rank[r] = slot index of the r-th free slot (one-hot
        # transpose scatter; the [A]-sized rank gather stays native)
        oh_rank = _onehot(jnp.where(free, free_rank, self.M), self.M)
        slot_of_rank = jnp.round(jnp.dot(slots.astype(jnp.float32), oh_rank,
                                         precision=_HI)).astype(jnp.int32)
        tgt = slot_of_rank[jnp.clip(arr_rank, 0, self.M - 1)]

        # one packed scatter per dtype instead of 11 per-field scatters —
        # scatters end XLA fusions, so per-substep op count (the TPU cost
        # driver) tracks the number of scatters, not the bytes moved
        arr_idx = jnp.where(spawn, tgt, self.M)
        a_i32 = jnp.zeros_like(cand)
        int_cur = jnp.stack([phase, node, position, F.sfc, F.egress, F.dest],
                            axis=-1)                           # [M, 6]
        int_new = jnp.stack([a_i32 + PH_DECIDE, traffic.arr_ingress[cand_c],
                             a_i32, traffic.arr_sfc[cand_c],
                             traffic.arr_egress[cand_c], a_i32 - 1],
                            axis=-1)                           # [A, 6]
        int_cur = int_cur.at[arr_idx].set(int_new, mode="drop")
        phase, node, position, sfc, egress, dest = (
            int_cur[:, 0], int_cur[:, 1], int_cur[:, 2], int_cur[:, 3],
            int_cur[:, 4], int_cur[:, 5])
        a_f32 = jnp.zeros(cand.shape, jnp.float32)
        flt_cur = jnp.stack([F.dr, F.duration, ttl, e2e, F.pend_path],
                            axis=-1)                           # [M, 5]
        flt_new = jnp.stack([traffic.arr_dr[cand_c],
                             traffic.arr_duration[cand_c],
                             traffic.arr_ttl[cand_c], a_f32, a_f32],
                            axis=-1)                           # [A, 5]
        flt_cur = flt_cur.at[arr_idx].set(flt_new, mode="drop")
        dr, duration, ttl, e2e, pend_path = (
            flt_cur[:, 0], flt_cur[:, 1], flt_cur[:, 2], flt_cur[:, 3],
            flt_cur[:, 4])
        hop_next = F.hop_next
        n_spawn = spawn.sum()
        cursor = state.cursor + n_spawn
        # arrivals spawning after their scheduled substep were delayed by
        # slot exhaustion / the per-substep arrival budget — count each once
        late = spawn & (traffic.arr_time[cand_c] < t - _EPS)
        truncated = state.truncated_arrivals + late.sum()
        m = m.replace(
            generated=m.generated + n_spawn,
            run_generated=m.run_generated + n_spawn,
            active=m.active + n_spawn,
            run_requested_node=m.run_requested_node.at[
                jnp.where(spawn, traffic.arr_ingress[cand_c], self.N)
            ].add(jnp.where(spawn, traffic.arr_dr[cand_c], 0.0), mode="drop"),
        )

        # recompute flags after arrivals.  The UN-clipped one-hot zero-rows
        # out-of-range SFC ids (reachable only with corrupt traffic data):
        # chain_len reads 0, so such a flow takes the to-egress path and
        # never reaches the WRR tables — a clamp would instead silently
        # attribute it to chain C-1 in run_requested / flow_counts.
        sfc_c = jnp.clip(sfc, 0, self.C - 1)
        oh_sfc = _onehot(sfc, self.C)
        chain_len = _take(jnp.asarray(self.tables.chain_len), oh_sfc)
        to_eg_flag = position >= chain_len

        # --- 4. decisions ---------------------------------------------------
        deciding = phase == PH_DECIDE
        # TTL exhausted at decision time -> drop (decide_next_node returns
        # None at ttl<=0, default_decision_maker.py:24-26; recorded as TTL,
        # metrics.py:158-160)
        drop_ttl0 = deciding & (ttl <= _EPS)
        decide = deciding & ~drop_ttl0
        to_eg = decide & to_eg_flag
        # flows with no egress depart at their current node
        # (default_decision_maker.py:28-31)
        egress = jnp.where(to_eg & (egress < 0), node, egress)
        wrr = decide & ~to_eg_flag

        sf_pos = jnp.clip(position, 0, self.S - 1)
        oh_cs = _onehot(sfc_c * self.S + sf_pos, self.C * self.S)
        sf_now = _take(jnp.asarray(self.tables.chain_sf).reshape(-1), oh_cs)
        sf_now = jnp.clip(sf_now, 0)
        oh_node = _onehot(node, self.N)                # [M, N]
        oh_sf = _onehot(sf_now, self.P)                # [M, P]
        # (node, sfc, sf_pos) cell one-hot, shared by the WRR table reads,
        # the counter updates, and the requested-traffic metric
        cell = (node * self.C + sfc_c) * self.S + sf_pos
        ncs = self.N * self.C * self.S
        oh_cell = _onehot(cell, ncs)                   # [M, NCS]
        placed = state.placed
        sf_startup = state.sf_startup
        sf_last_active = state.sf_last_active
        if ext_decisions is None:
            # requested-traffic metric for every WRR decision, before the
            # schedule lookup (add_requesting_flow,
            # default_decision_maker.py:35-36)
            req_add = jnp.dot(jnp.where(wrr, dr, 0.0), oh_cell,
                              precision=_HI).reshape(m.run_requested.shape)
            m = m.replace(run_requested=m.run_requested + req_add)

            # WRR over the schedule row with realized-ratio counters
            # (default_decision_maker.py:42-66); same-cell same-substep
            # collisions run in slot-order rounds so later flows see updated
            # counters
            rank = _rank_in_cell(cell, wrr, ncs)
            flow_counts = m.run_flow_counts
            # schedule rows are loop-invariant (indexed by chain POSITION;
            # its SF axis mirrors the action layout, environment_limits.py:
            # 44-51)
            probs = _take(state.schedule.reshape(ncs, self.N), oh_cell)
            R = self.cfg.wrr_rank_levels
            for r in range(R):
                sel = wrr & ((rank == r) if r < R - 1 else (rank >= r))
                counts = _take(flow_counts.reshape(ncs, self.N), oh_cell)
                total = counts.sum(-1, keepdims=True)
                ratios = jnp.where(total > 0, counts / jnp.maximum(total, 1), 0.0)
                diffs = jnp.where(probs > 0, probs - ratios, -1.0)
                choice = jnp.argmax(diffs, axis=-1).astype(jnp.int32)
                dest = jnp.where(sel, choice, dest)
                cnt_add = jnp.einsum(
                    "mc,mn->cn", oh_cell * sel[:, None].astype(jnp.float32),
                    _onehot(choice, self.N), precision=_HI)
                flow_counts = flow_counts + jnp.round(cnt_add).astype(
                    flow_counts.dtype).reshape(flow_counts.shape)
            m = m.replace(run_flow_counts=flow_counts)
        else:
            # per-flow external control: only flows with a provided decision
            # proceed; the rest stay parked in DECIDE (flow_trigger blocking,
            # external_decision_maker.py:45-53)
            has_dec = ext_decisions >= 0
            wrr = wrr & has_dec
            dest = jnp.where(wrr, jnp.clip(ext_decisions, 0, self.N - 1), dest)
            req_add = jnp.dot(jnp.where(wrr, dr, 0.0), oh_cell,
                              precision=_HI).reshape(m.run_requested.shape)
            m = m.replace(run_requested=m.run_requested + req_add)
            # place-on-decision (flow_controller.py:46-60): install the SF at
            # the decided node if absent, stamping its startup time
            newly_placed = jnp.einsum(
                "mn,mp->np", _onehot(dest, self.N) * wrr[:, None].astype(
                    jnp.float32), oh_sf, precision=_HI) > 0.5
            newly_placed = newly_placed & ~placed
            placed = placed | newly_placed
            fresh = newly_placed & ~sf_available
            sf_startup = jnp.where(fresh, t, sf_startup)
            sf_last_active = jnp.where(newly_placed, t, sf_last_active)
            sf_available = sf_available | newly_placed
        dest = jnp.where(to_eg, egress, dest)

        # --- 5. forwarding --------------------------------------------------
        fwd = (to_eg | wrr) if ext_decisions is not None else decide
        stay = fwd & (dest == node)
        depart_stay = to_eg & stay                    # at egress already
        need_proc_b = wrr & stay
        start_path = fwd & ~stay
        # All node-indexed table rows come out of ONE wide one-hot dot:
        # [path_delay | next_hop | adj_edge_id | cap_now] is loop-invariant
        # (XLA hoists the concat out of the substep scan), so 4 gather-dots
        # collapse into a single [M,N]@[N,3N+1] contraction.  inf path
        # delays (unreachable) become a big finite value so the 0*inf=NaN
        # dot hazard never arises — every use compares against TTL
        # (<= 1e4), for which 1e30 and inf behave identically.
        oh_dest = _onehot(jnp.clip(dest, 0), self.N)
        pd_tab = jnp.where(jnp.isfinite(topo.path_delay), topo.path_delay,
                           1e30)
        static_tab = jnp.concatenate(
            [pd_tab, topo.next_hop.astype(jnp.float32),
             topo.adj_edge_id.astype(jnp.float32), cap_now[:, None]],
            axis=1)                                    # [N, 3N+1]
        rows = jnp.dot(oh_node, static_tab, precision=_HI)  # [M, 3N+1]
        pd_rows = rows[:, :self.N]
        nh_rows = rows[:, self.N:2 * self.N]
        adj_rows = rows[:, 2 * self.N:3 * self.N]
        cap_mine = rows[:, 3 * self.N]
        pd_path = (pd_rows * oh_dest).sum(-1)
        # upfront whole-path TTL check (default_forwarder.py:35-39);
        # unreachable destinations have inf path delay and also drop here
        drop_ttl_path = start_path & (ttl - pd_path <= _EPS)
        ttl = jnp.where(drop_ttl_path, 0.0, ttl)
        start_path = start_path & ~drop_ttl_path

        # hop starts this substep: fresh paths + mid-path continuations
        hop_req = cont | start_path
        nh = jnp.round((nh_rows * oh_dest).sum(-1)).astype(jnp.int32)
        nh = jnp.clip(nh, 0)
        eid = jnp.round((adj_rows * _onehot(nh, self.N)).sum(-1)
                        ).astype(jnp.int32)
        eid_c = jnp.clip(eid, 0)
        oh_e = _onehot(eid_c, self.E)                  # [M, E]
        edge_rows = _take(jnp.stack(
            [topo.edge_cap - edge_used + _EPS, topo.edge_delay],
            axis=-1), oh_e)                            # [M, 2]
        headroom = edge_rows[:, 0]

        # Hoisted stage-6 pre-sort work: the node-admission pipeline's sort
        # inputs (want/dr/cap_mine) do not depend on LINK admission, so
        # both grouping pipelines batch into ONE vmapped argsort + ONE
        # [2,M,M]x[2,M,4] permutation contraction + ONE run-starts pass —
        # halving the per-substep op count of the sort machinery (op count,
        # not bytes, bounds the substep on the measured chip).
        need_proc = need_proc_a | need_proc_b
        # [placed | sf_startup] rows in one dot (loop-variant in per-flow
        # control mode, so kept separate from the static table above)
        ps_rows = jnp.dot(oh_node, jnp.concatenate(
            [placed.astype(jnp.float32), sf_startup], axis=1),
            precision=_HI)                             # [M, 2P]
        sf_ok = (ps_rows[:, :self.P] * oh_sf).sum(-1) > 0.5
        # SF not in placement -> drop (default_processor.py:48-50 ->
        # NODE_CAP, flowsimulator.py:114-118)
        drop_unplaced = need_proc & ~sf_ok
        want = need_proc & sf_ok
        proc_tab = _take(jnp.stack(
            [jnp.asarray(self.tables.proc_mean),
             jnp.asarray(self.tables.proc_std),
             jnp.asarray(self.tables.startup_delay)], axis=-1), oh_sf)
        pmean = proc_tab[:, 0]
        pstd = proc_tab[:, 1]
        if self._det_proc:
            # fully deterministic processing delays (the flagship abc.yaml
            # case): |N(mean, 0)| == mean, so skip the per-substep threefry
            # draw entirely — measured ~10% of substep wall (r3 profile).
            # The k_proc split above still happens, so the rng STREAM of
            # every other consumer is unchanged (bit-exact goldens).
            pdel = jnp.abs(pmean)   # |N(mean, 0)| — abs matters if a
            # config carries a negative delay mean (nothing rejects one)
        else:
            pdel = jnp.abs(jax.random.normal(k_proc, (self.M,)) * pstd
                           + pmean)
        # TTL check before the delay is credited (base_processor.py:37-44);
        # want-flows are disjoint from every stage-5 ttl write, so the
        # check reads the same values it did when it lived in stage 6
        drop_ttl_pd = want & (ttl - pdel <= _EPS)
        want = want & ~drop_ttl_pd

        # batched slot-order grouping for link (b=0) and node (b=1)
        # admission (deduct_link_resources, default_forwarder.py:95-111;
        # request_resources, base_processor.py:51-101).  Groupings are
        # fixed across refinement iterations (only ``admitted`` changes):
        # sort once, redo only the masked cumsum per iteration; all
        # permutation gathers/scatters are one-hot dots.
        keys2 = jnp.stack([eid_c, node])               # [2, M]
        orders2 = jax.vmap(_group_order)(keys2)
        perms2 = jax.vmap(lambda o: _onehot(o, self.M))(orders2)
        sort_ins = jnp.stack([
            jnp.stack([eid_c.astype(jnp.float32),
                       (hop_req & (eid >= 0)).astype(jnp.float32),
                       dr, headroom], axis=-1),
            jnp.stack([node.astype(jnp.float32), want.astype(jnp.float32),
                       dr, cap_mine], axis=-1)])       # [2, M, 4]
        sorted2 = jnp.einsum("bmn,bnk->bmk", perms2, sort_ins,
                             precision=_HI)
        keys_sorted = jnp.round(sorted2[:, :, 0]).astype(jnp.int32)
        starts2 = jax.vmap(_run_starts)(keys_sorted)
        oh_starts2 = jax.vmap(lambda s: _onehot(s, self.M))(starts2)

        perm_e = perms2[0]
        eid_s = keys_sorted[0]
        req_s = sorted2[0, :, 1] > 0.5
        dr_s = sorted2[0, :, 2]
        headroom_s = sorted2[0, :, 3]
        oh_starts_e = oh_starts2[0]
        adm_s = req_s
        for _ in range(self.cfg.admission_iters):
            v = jnp.where(adm_s, dr_s, 0.0)
            cs = jnp.cumsum(v)
            bound = jnp.dot(oh_starts_e, jnp.stack([cs, v], axis=-1),
                            precision=_HI)
            adm_s = req_s & (cs - (bound[:, 0] - bound[:, 1]) <= headroom_s)
        admitted = jnp.dot(adm_s.astype(jnp.float32), perm_e,
                           precision=_HI) > 0.5
        drop_link = hop_req & ~admitted
        add_e = jnp.where(admitted, dr, 0.0)
        edge_add = jnp.dot(add_e, oh_e, precision=_HI)  # [E]
        edge_used = edge_used + edge_add
        m = m.replace(run_passed_traffic=m.run_passed_traffic + edge_add)
        hop_delay = edge_rows[:, 1]
        # release link capacity hop_delay + duration after the hop starts
        # (default_forwarder.py:112-125)
        off_e = jnp.clip(jnp.ceil((hop_delay + duration) / dt).astype(jnp.int32),
                         1, self.H - 1)
        oh_off_e = _onehot(jnp.where(admitted, jnp.mod(ridx + off_e, self.H),
                                     self.H), self.H)  # [M, H]
        rel_edge = rel_edge + jnp.einsum(
            "mh,me->he", oh_off_e, oh_e * add_e[:, None], precision=_HI)
        pend_path = jnp.where(start_path & admitted, pd_path, pend_path)
        hop_next = jnp.where(admitted, nh, hop_next)
        timer = jnp.where(admitted, hop_delay, timer)
        phase = jnp.where(admitted, PH_HOP, phase)

        # --- 6. processing --------------------------------------------------
        # (need_proc/sf_ok/want/pdel and the node grouping were computed
        # with the batched sort machinery above, before link admission)
        ttl = jnp.where(drop_ttl_pd, 0.0, ttl)
        e2e = e2e + jnp.where(want, pdel, 0.0)
        ttl = ttl - jnp.where(want, pdel, 0.0)
        n_want = want.sum()
        m = m.replace(
            sum_proc_delay=m.sum_proc_delay + jnp.where(want, pdel, 0.0).sum(),
            num_proc_delay=m.num_proc_delay + n_want,
        )
        # node capacity admission via resource functions, greedy slot order
        # (request_resources, base_processor.py:51-101).  Every candidate
        # sees the base load plus the same-substep admitted drs of flows
        # m'<=m at its node, per SF column: one (node, slot) grouping reused
        # across refinement iters, with a single [M,P] cumsum per iter — no
        # [M, N*S] materialization, no per-SF Python loop.
        perm_n = perms2[1]
        node_sorted = keys_sorted[1]
        want_s = sorted2[1, :, 1] > 0.5
        dr_col_s = sorted2[1, :, 2][:, None]
        cap_s = sorted2[1, :, 3]
        oh_starts_n = oh_starts2[1]
        oh_ns = _onehot(node_sorted, self.N)
        la_rows = jnp.dot(oh_ns, jnp.concatenate(
            [node_load, sf_available.astype(jnp.float32)], axis=1),
            precision=_HI)                             # [M, 2P]
        base_load_s = la_rows[:, :self.P]
        avail_s = la_rows[:, self.P:] > 0.5
        sf_onehot_s = jnp.dot(perm_n, oh_sf, precision=_HI) > 0.5
        adm_ns = want_s
        dem_s = jnp.zeros(self.M, jnp.float32)
        for _ in range(self.cfg.admission_iters):
            v = jnp.where(adm_ns[:, None] & sf_onehot_s, dr_col_s, 0.0)
            cs = jnp.cumsum(v, axis=0)
            b_cs = jnp.dot(oh_starts_n, cs, precision=_HI)
            b_v = jnp.dot(oh_starts_n, v, precision=_HI)
            dem_s = self._demanded(base_load_s + cs - (b_cs - b_v), avail_s)
            adm_ns = want_s & (dem_s <= cap_s + _EPS)
        unsorted = jnp.dot(
            jnp.stack([adm_ns.astype(jnp.float32), dem_s], axis=-1).T,
            perm_n, precision=_HI)                             # [2, M]
        admitted_n = unsorted[0] > 0.5
        demanded = unsorted[1]
        drop_nodecap = want & ~admitted_n
        add_n = jnp.where(admitted_n, dr, 0.0)
        node_add = jnp.einsum("mn,mp->np", oh_node * add_n[:, None], oh_sf,
                              precision=_HI)                   # [N, P]
        node_load = node_load + node_add
        m = m.replace(
            run_processed_traffic=m.run_processed_traffic + node_add,
            run_max_node_usage=jnp.maximum(
                m.run_max_node_usage,
                (oh_node * jnp.where(admitted_n, demanded, 0.0)[:, None]
                 ).max(axis=0)),
        )
        # startup wait (base_processor.py:79-97); a TTL expiry here releases
        # the load immediately (divergence: the reference leaks it)
        sw = jnp.maximum(
            (ps_rows[:, self.P:] * oh_sf).sum(-1)
            + proc_tab[:, 2] - t, 0.0)
        drop_ttl_sw = admitted_n & (ttl - sw <= _EPS) & (sw > _EPS)
        ttl = jnp.where(drop_ttl_sw, 0.0, ttl)
        started = admitted_n & ~drop_ttl_sw
        e2e = e2e + jnp.where(started, sw, 0.0)
        ttl = ttl - jnp.where(started, sw, 0.0)
        busy = jnp.where(started, sw + pdel, 0.0)
        timer = jnp.where(started, busy, timer)
        phase = jnp.where(started, PH_PROC, phase)
        # release node load busy + duration after processing starts
        # (finish_processing waits flow.duration after the delay elapses,
        # base_processor.py:103-112); TTL-in-startup drops release now
        hold = jnp.where(started, busy + duration, dt)
        rel_who = started | drop_ttl_sw
        off_n = jnp.clip(jnp.ceil(hold / dt).astype(jnp.int32), 1, self.H - 1)
        oh_off_n = _onehot(jnp.where(rel_who, jnp.mod(ridx + off_n, self.H),
                                     self.H), self.H)          # [M, H]
        rel_vals = jnp.where(rel_who, dr, 0.0)
        np_flat = jnp.einsum("mn,mp->mnp", oh_node * rel_vals[:, None],
                             oh_sf, precision=_HI
                             ).reshape(self.M, self.N * self.P)
        rel_node = rel_node + jnp.einsum("mh,mk->hk", oh_off_n, np_flat,
                                         precision=_HI)

        # --- 7. departures & drops -----------------------------------------
        depart = depart_hop | depart_stay
        n_dep = depart.sum()
        dep_e2e = jnp.where(depart, e2e, 0.0)
        m = m.replace(
            processed=m.processed + n_dep,
            run_processed=m.run_processed + n_dep,
            sum_e2e=m.sum_e2e + dep_e2e.sum(),
            run_e2e_sum=m.run_e2e_sum + dep_e2e.sum(),
            run_e2e_max=jnp.maximum(m.run_e2e_max, dep_e2e.max()),
            active=m.active - n_dep,
        )
        drops = [
            (drop_ttl0, DROP_DECISION),
            (drop_ttl_path, DROP_LINK_CAP),
            (drop_link, DROP_LINK_CAP),
            (drop_unplaced, DROP_NODE_CAP),
            (drop_ttl_pd, DROP_NODE_CAP),
            (drop_nodecap, DROP_NODE_CAP),
            (drop_ttl_sw, DROP_NODE_CAP),
        ]
        any_drop = jnp.zeros(self.M, bool)
        n_reasons = m.drop_reasons.shape[0]
        adds = [jnp.zeros((), m.drop_reasons.dtype)] * n_reasons
        for mask, reason in drops:
            any_drop = any_drop | mask
            # ttl<=0 always recorded as TTL (metrics.py:158-160)
            is_ttl = mask & (ttl <= _EPS)
            adds[DROP_TTL] = adds[DROP_TTL] + is_ttl.sum()
            adds[reason] = adds[reason] + (mask & ~is_ttl).sum()
        reasons = m.drop_reasons + jnp.stack(adds)
        n_drop = any_drop.sum()
        m = m.replace(
            drop_reasons=reasons,
            dropped=m.dropped + n_drop,
            run_dropped=m.run_dropped + n_drop,
            active=m.active - n_drop,
            run_dropped_per_node=m.run_dropped_per_node + jnp.round(
                jnp.dot(any_drop.astype(jnp.float32), oh_node,
                        precision=_HI)).astype(m.run_dropped_per_node.dtype),
        )
        gone = depart | any_drop
        phase = jnp.where(gone, PH_FREE, phase)

        # idle-VNF bookkeeping: instances with load refresh last_active; in
        # per-flow control mode instances idle past vnf_timeout are removed
        # (update_vnf_active_status, flow_controller.py:94-112 — the
        # reference only garbage-collects under FlowController)
        active_sf = node_load > _EPS
        sf_last_active = jnp.where(active_sf, t, sf_last_active)
        if self.cfg.controller == "per_flow":
            expire = sf_available & ~active_sf & (
                sf_last_active < t - self.cfg.vnf_timeout)
            sf_available = sf_available & ~expire
            placed = placed & ~expire

        flows = FlowTable(phase=phase, sfc=sfc, position=position, node=node,
                          dest=dest, hop_next=hop_next, egress=egress, dr=dr,
                          duration=duration, ttl=ttl, e2e=e2e,
                          pend_path=pend_path, timer=timer)
        return state.replace(
            t=t + dt, flows=flows, cursor=cursor, node_load=node_load,
            sf_available=sf_available, edge_used=edge_used,
            placed=placed, sf_startup=sf_startup,
            sf_last_active=sf_last_active,
            rel_node=rel_node, rel_edge=rel_edge, metrics=m, rng=rng,
            truncated_arrivals=truncated,
        )
