"""Traffic prediction — analytic look-ahead + learned RNN forecaster.

Reference: the dormant traffic-forecasting subsystem
(coordsim/traffic_predictor/traffic_predictor.py:22-56 — analytic look-ahead
over the pregenerated flow lists, overwriting the requested-traffic metric
the observation builder reads — and lstm_predictor.py:16-307, a Keras
stateful-LSTM one-step forecaster; dead code upstream since keras is not in
its requirements, SURVEY.md §2).  Both capabilities, alive:

- ``predict_ingress_traffic``: per-node data-rate sum of the arrivals in the
  *next* control interval, straight from the TrafficSchedule tensors — pure
  jnp, usable inside the jitted observation path (enable with
  ``SimConfig.prediction``; the env then shows upcoming instead of observed
  ingress traffic, mirroring traffic_predictor.py:28-56).
- ``RNNTrafficPredictor``: a flax GRU one-step forecaster over the
  per-interval traffic series with min-max scaling, the LSTM_Predictor
  analogue (train on a trace, predict the next interval's total dr).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .state import TrafficSchedule


def predict_ingress_traffic(traffic: TrafficSchedule, run_idx: jnp.ndarray,
                            run_duration: float, n_nodes: int) -> jnp.ndarray:
    """[N] predicted ingress dr for control interval ``run_idx`` (the
    analytic branch of traffic_predictor.py:43-49: every flow arriving
    before the interval's end contributes its dr)."""
    t0 = run_idx.astype(jnp.float32) * run_duration
    t1 = t0 + run_duration
    in_window = (traffic.arr_time >= t0) & (traffic.arr_time < t1) \
        & jnp.isfinite(traffic.arr_time)
    return jnp.zeros(n_nodes).at[
        jnp.where(in_window, traffic.arr_ingress, n_nodes)
    ].add(jnp.where(in_window, traffic.arr_dr, 0.0), mode="drop")


def interval_traffic_series(traffic: TrafficSchedule, run_duration: float,
                            episode_steps: int, n_nodes: int) -> np.ndarray:
    """[T, N] per-interval ingress dr — training data for the learned
    predictor (lstm_predictor.py gen_training_data analogue)."""
    times = np.asarray(traffic.arr_time)
    ing = np.asarray(traffic.arr_ingress)
    drs = np.asarray(traffic.arr_dr)
    fin = np.isfinite(times)
    out = np.zeros((episode_steps, n_nodes), np.float32)
    k = np.minimum((times[fin] / run_duration).astype(int), episode_steps - 1)
    np.add.at(out, (k, ing[fin]), drs[fin])
    return out


class _GRUForecaster(nn.Module):
    hidden: int = 16

    @nn.compact
    def __call__(self, series):
        """series: [T, 1] -> [T, 1] one-step-ahead predictions."""
        scan_cell = nn.scan(nn.GRUCell, variable_broadcast="params",
                            split_rngs={"params": False},
                            in_axes=0, out_axes=0)(features=self.hidden)
        carry = jnp.zeros((1, self.hidden), series.dtype)
        _, hs = scan_cell(carry, series[:, None, :])     # [T, 1, H]
        return nn.Dense(1)(hs[:, 0])


class RNNTrafficPredictor:
    """One-step traffic forecaster (LSTM_Predictor analogue,
    lstm_predictor.py:16-307): min-max scale the per-interval traffic
    series, train a GRU to predict the next value, query step by step."""

    def __init__(self, hidden: int = 16, lr: float = 1e-2, seed: int = 0):
        self.model = _GRUForecaster(hidden=hidden)
        self.seed = seed
        self.lr = lr
        self.params = None
        self.lo = 0.0
        self.hi = 1.0

    def _scale(self, x):
        return (x - self.lo) / max(self.hi - self.lo, 1e-9)

    def _unscale(self, y):
        return y * max(self.hi - self.lo, 1e-9) + self.lo

    def fit(self, series: np.ndarray, epochs: int = 300) -> float:
        """Train on a 1-D per-interval traffic series; returns final MSE
        (scaled space)."""
        import optax

        series = np.asarray(series, np.float32)
        self.lo, self.hi = float(series.min()), float(series.max())
        s = self._scale(series)[:, None]
        x, y = jnp.asarray(s[:-1]), jnp.asarray(s[1:])
        params = self.model.init(jax.random.PRNGKey(self.seed), x)
        opt = optax.adam(self.lr)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                pred = self.model.apply(p, x)
                return jnp.mean((pred - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            upd, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, upd), opt_state, loss

        loss = jnp.inf
        for _ in range(epochs):
            params, opt_state, loss = step(params, opt_state)
        self.params = params
        return float(loss)

    def predict(self, history: np.ndarray) -> float:
        """Next-interval traffic given the observed history
        (lstm_predictor.predict_traffic analogue)."""
        if self.params is None:
            raise RuntimeError("fit() first")
        s = self._scale(np.asarray(history, np.float32))[:, None]
        pred = self.model.apply(self.params, jnp.asarray(s))
        return float(self._unscale(np.asarray(pred)[-1, 0]))
