"""Per-flow control — host-driven controller over the batched engine.

The reference's second control granularity (SURVEY.md §3.5): instead of one
(placement, schedule) action per control interval, an external algorithm
decides each flow's next node individually.  In the reference the simulator
blocks on a SimPy ``flow_trigger`` event and hands the waiting flow to the
algorithm as an ``SPRState`` (coordsim/controller/flow_controller.py:21-92,
external_decision_maker.py:20-53).

Here the fixed-step engine exposes ``SimEngine.apply_substep(state, ...,
ext_decisions)``: flows reaching a decision point park in the DECIDE phase
until a decision arrives (quantized to the next substep — documented
divergence of the fixed-step design).  Two drivers:

- ``PerFlowController`` (this module): host loop that advances substeps until
  flows are waiting, surfaces them as a ``PendingFlows`` record (the
  SPRState analogue), and injects the caller's decisions — for external,
  non-JAX algorithms.
- ``SimEngine.apply_per_flow(state, topo, traffic, decide_fn)``: fully
  on-device variant where ``decide_fn`` is a jitted policy invoked every
  substep — the TPU-native path (no reference analogue; the reference cannot
  batch per-flow control at all).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .engine import SimEngine
from .state import PH_DECIDE, SimState, TrafficSchedule
from ..topology.compiler import Topology


@dataclass
class PendingFlows:
    """Flows waiting for an external decision plus the network view — the
    full SPRState analogue (flow_controller.py:10-18: flow + network + sfcs
    + network_stats), so per-flow algorithms never have to dig into SimState
    themselves.

    Per-flow fields are [K] over the waiting flows; network-view fields are
    full-size ([N]/[E]/[N,P]) snapshots at the current substep."""

    slots: np.ndarray      # [K] flow-table slot indices
    node: np.ndarray       # [K] current node
    sfc: np.ndarray        # [K]
    position: np.ndarray   # [K] chain position
    sf: np.ndarray         # [K] SF id needed next (chain_sf[sfc, position])
    dr: np.ndarray         # [K]
    ttl: np.ndarray        # [K]
    egress: np.ndarray     # [K] egress node (-1: none)
    t: float               # current sim time (ms)
    # --- network view (SimulatorState.network / network_stats parity:
    # parse_network remaining caps + available_sf placement,
    # simulator.py:176-202; network_metrics counters, metrics.py) ---
    node_cap: np.ndarray       # [N] current interval node capacities
    node_remaining: np.ndarray  # [N] cap minus current processed load
    edge_cap: np.ndarray       # [E]
    edge_remaining: np.ndarray  # [E] cap minus in-flight dr
    sf_available: np.ndarray   # [N,P] bool: SF placed or still draining
    path_delay: np.ndarray     # [N,N] all-pairs shortest path delay (ms)
    network_stats: dict        # in_network/processed/dropped totals

    def __len__(self):
        return len(self.slots)


class PerFlowController:
    """Host-side per-flow control loop (FlowController.get_init_state /
    get_next_state semantics, flow_controller.py:30-92)."""

    def __init__(self, engine: SimEngine, topo: Topology,
                 traffic: TrafficSchedule, writer=None, episode: int = 0):
        self.engine = engine
        self.topo = topo
        self.traffic = traffic
        self._none = jnp.full(engine.M, -1, jnp.int32)
        # optional TestModeWriter with write_flow_actions for per-decision
        # telemetry rows (writer.py:112-140)
        self.writer = writer
        self.episode = episode

    def _network_view(self, state: SimState):
        """Current-interval capacity/placement snapshot (the controller's
        parse_network step, flow_controller.py:34-41)."""
        node_cap = np.asarray(
            self.traffic.node_cap[min(int(state.run_idx),
                                      self.traffic.node_cap.shape[0] - 1)])
        node_rem = node_cap - np.asarray(state.node_load).sum(axis=-1)
        edge_cap = np.asarray(self.topo.edge_cap)
        edge_rem = edge_cap - np.asarray(state.edge_used)
        return node_cap, node_rem, edge_cap, edge_rem

    def _waiting_slots(self, state: SimState) -> np.ndarray:
        """Slot indices of flows parked in DECIDE (cheap: flow arrays only —
        polled every substep, so no network-view work here)."""
        f = state.flows
        waiting = np.asarray(f.phase == PH_DECIDE)
        chain_len = self.engine.tables.chain_len[np.asarray(f.sfc)]
        # egress routing stays automatic; only SF-position decisions wait
        waiting = waiting & (np.asarray(f.position) < chain_len)
        return np.nonzero(waiting)[0]

    def _pending(self, state: SimState) -> PendingFlows:
        f = state.flows
        tables = self.engine.tables
        slots = self._waiting_slots(state)
        sfc_all = np.asarray(f.sfc)
        pos_all = np.asarray(f.position)
        chain_len = tables.chain_len[sfc_all]
        sfc = sfc_all[slots]
        pos = pos_all[slots]
        node_cap, node_rem, edge_cap, edge_rem = self._network_view(state)
        m = state.metrics
        return PendingFlows(
            slots=slots, node=np.asarray(f.node)[slots], sfc=sfc,
            position=pos,
            sf=tables.chain_sf[sfc, np.minimum(pos, chain_len[slots] - 1)],
            dr=np.asarray(f.dr)[slots], ttl=np.asarray(f.ttl)[slots],
            egress=np.asarray(f.egress)[slots],
            t=float(state.t),
            node_cap=node_cap, node_remaining=node_rem,
            edge_cap=edge_cap, edge_remaining=edge_rem,
            sf_available=np.asarray(state.sf_available),
            path_delay=np.asarray(self.topo.path_delay),
            network_stats={
                "total_flows": int(m.generated),
                "successful_flows": int(m.processed),
                "dropped_flows": int(m.dropped),
                "in_network_flows": int(m.active),
            })

    def run_until_decision(self, state: SimState, max_substeps: int = 10_000
                           ) -> tuple[SimState, PendingFlows]:
        """Advance substeps until at least one flow waits for a decision or
        the substep budget is exhausted (the env.run-until-flow_trigger loop,
        flow_controller.py:30-42)."""
        for _ in range(max_substeps):
            if len(self._waiting_slots(state)):
                return state, self._pending(state)
            state = self.engine.apply_substep(state, self.topo, self.traffic,
                                              self._none)
        return state, self._pending(state)

    def decide(self, state: SimState, pending: PendingFlows,
               destinations: np.ndarray) -> SimState:
        """Apply the algorithm's decisions (destination node per pending
        flow; -1 leaves a flow waiting) and advance one substep
        (FlowController.get_next_state, flow_controller.py:44-71)."""
        dec = np.full(self.engine.M, -1, np.int32)
        dec[pending.slots] = destinations
        if self.writer is not None:
            self._log_decisions(pending, destinations)
        return self.engine.apply_substep(state, self.topo, self.traffic,
                                         jnp.asarray(dec))

    def _log_decisions(self, pending: PendingFlows,
                       destinations: np.ndarray) -> None:
        # the pending record snapshots the deciding state's network view
        node_rem = pending.node_remaining
        edge_cap = pending.edge_cap
        edge_rem = pending.edge_remaining
        adj = np.asarray(self.topo.adj_edge_id)
        for i, slot in enumerate(pending.slots):
            dest = int(destinations[i])
            cur = int(pending.node[i])
            if dest < 0:
                dst_repr, next_rem, lcap, lrem = "None", -1, -1, -1
            elif dest == cur:
                dst_repr, next_rem = dest, node_rem[dest]
                lcap = lrem = "inf"  # same-node: no link (writer.py:124-127)
            else:
                eid = int(adj[cur, dest])
                dst_repr, next_rem = dest, node_rem[dest]
                lcap = edge_cap[eid] if eid >= 0 else -1
                lrem = edge_rem[eid] if eid >= 0 else -1
            self.writer.write_flow_action(
                self.episode, pending.t, int(slot),
                float(pending.ttl[i]), float(pending.ttl[i]), cur, dst_repr,
                node_rem[cur], next_rem, lcap, lrem)
