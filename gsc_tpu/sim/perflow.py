"""Per-flow control — host-driven controller over the batched engine.

The reference's second control granularity (SURVEY.md §3.5): instead of one
(placement, schedule) action per control interval, an external algorithm
decides each flow's next node individually.  In the reference the simulator
blocks on a SimPy ``flow_trigger`` event and hands the waiting flow to the
algorithm as an ``SPRState`` (coordsim/controller/flow_controller.py:21-92,
external_decision_maker.py:20-53).

Here the fixed-step engine exposes ``SimEngine.apply_substep(state, ...,
ext_decisions)``: flows reaching a decision point park in the DECIDE phase
until a decision arrives (quantized to the next substep — documented
divergence of the fixed-step design).  Two drivers:

- ``PerFlowController`` (this module): host loop that advances substeps until
  flows are waiting, surfaces them as a ``PendingFlows`` record (the
  SPRState analogue), and injects the caller's decisions — for external,
  non-JAX algorithms.
- ``SimEngine.apply_per_flow(state, topo, traffic, decide_fn)``: fully
  on-device variant where ``decide_fn`` is a jitted policy invoked every
  substep — the TPU-native path (no reference analogue; the reference cannot
  batch per-flow control at all).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .engine import SimEngine
from .state import PH_DECIDE, SimState, TrafficSchedule
from ..topology.compiler import Topology


@dataclass
class PendingFlows:
    """Flows waiting for an external decision (the SPRState analogue,
    flow_controller.py:73-92: flow + network view)."""

    slots: np.ndarray      # [K] flow-table slot indices
    node: np.ndarray       # [K] current node
    sfc: np.ndarray        # [K]
    position: np.ndarray   # [K] chain position
    dr: np.ndarray         # [K]
    ttl: np.ndarray        # [K]
    t: float               # current sim time (ms)

    def __len__(self):
        return len(self.slots)


class PerFlowController:
    """Host-side per-flow control loop (FlowController.get_init_state /
    get_next_state semantics, flow_controller.py:30-92)."""

    def __init__(self, engine: SimEngine, topo: Topology,
                 traffic: TrafficSchedule, writer=None, episode: int = 0):
        self.engine = engine
        self.topo = topo
        self.traffic = traffic
        self._none = jnp.full(engine.M, -1, jnp.int32)
        # optional TestModeWriter with write_flow_actions for per-decision
        # telemetry rows (writer.py:112-140)
        self.writer = writer
        self.episode = episode

    def _pending(self, state: SimState) -> PendingFlows:
        f = state.flows
        waiting = np.asarray(f.phase == PH_DECIDE)
        chain_len = self.engine.tables.chain_len[np.asarray(f.sfc)]
        # egress routing stays automatic; only SF-position decisions wait
        waiting = waiting & (np.asarray(f.position) < chain_len)
        slots = np.nonzero(waiting)[0]
        return PendingFlows(
            slots=slots, node=np.asarray(f.node)[slots],
            sfc=np.asarray(f.sfc)[slots],
            position=np.asarray(f.position)[slots],
            dr=np.asarray(f.dr)[slots], ttl=np.asarray(f.ttl)[slots],
            t=float(state.t))

    def run_until_decision(self, state: SimState, max_substeps: int = 10_000
                           ) -> tuple[SimState, PendingFlows]:
        """Advance substeps until at least one flow waits for a decision or
        the substep budget is exhausted (the env.run-until-flow_trigger loop,
        flow_controller.py:30-42)."""
        for _ in range(max_substeps):
            pending = self._pending(state)
            if len(pending):
                return state, pending
            state = self.engine.apply_substep(state, self.topo, self.traffic,
                                              self._none)
        return state, self._pending(state)

    def decide(self, state: SimState, pending: PendingFlows,
               destinations: np.ndarray) -> SimState:
        """Apply the algorithm's decisions (destination node per pending
        flow; -1 leaves a flow waiting) and advance one substep
        (FlowController.get_next_state, flow_controller.py:44-71)."""
        dec = np.full(self.engine.M, -1, np.int32)
        dec[pending.slots] = destinations
        if self.writer is not None:
            self._log_decisions(state, pending, destinations)
        return self.engine.apply_substep(state, self.topo, self.traffic,
                                         jnp.asarray(dec))

    def _log_decisions(self, state: SimState, pending: PendingFlows,
                       destinations: np.ndarray) -> None:
        node_cap = np.asarray(
            self.traffic.node_cap[min(int(state.run_idx),
                                      self.traffic.node_cap.shape[0] - 1)])
        node_rem = node_cap - np.asarray(state.node_load).sum(axis=-1)
        edge_cap = np.asarray(self.topo.edge_cap)
        edge_rem = edge_cap - np.asarray(state.edge_used)
        adj = np.asarray(self.topo.adj_edge_id)
        for i, slot in enumerate(pending.slots):
            dest = int(destinations[i])
            cur = int(pending.node[i])
            if dest < 0:
                dst_repr, next_rem, lcap, lrem = "None", -1, -1, -1
            elif dest == cur:
                dst_repr, next_rem = dest, node_rem[dest]
                lcap = lrem = "inf"  # same-node: no link (writer.py:124-127)
            else:
                eid = int(adj[cur, dest])
                dst_repr, next_rem = dest, node_rem[dest]
                lcap = edge_cap[eid] if eid >= 0 else -1
                lrem = edge_rem[eid] if eid >= 0 else -1
            self.writer.write_flow_action(
                self.episode, float(state.t), int(slot),
                float(pending.ttl[i]), float(pending.ttl[i]), cur, dst_repr,
                node_rem[cur], next_rem, lcap, lrem)
