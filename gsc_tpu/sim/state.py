"""Simulator state pytrees.

The reference keeps per-flow state in Python ``Flow`` objects driven by SimPy
processes (coordsim/network/flow.py:10-48, coordsim/simulation/
flowsimulator.py:59-128) and network state as networkx node/edge attribute
dicts.  Here the whole simulation is a fixed-shape pytree so it can live in
TPU HBM, be advanced by ``lax.scan`` and batched with ``vmap``:

- ``FlowTable``: a preallocated table of MAX_FLOWS flow slots (struct of
  arrays), the functional replacement for dynamically spawned SimPy processes.
- ``SimMetrics``: the counters of coordsim/metrics/metrics.py:15-230 as flat
  arrays, with the same cumulative vs per-run split (run metrics reset each
  control interval, coordsim/writer/writer.py:222-225).
- ``SimState``: everything that changes during an episode — flow table, per
  (node, SF) load/availability/startup bookkeeping (the reference's
  ``available_sf`` node attribute, simulatorparams.py:66-73), per-edge in-
  flight data rate (``remaining_cap`` edge attribute,
  default_forwarder.py:100-125), capacity-release ring buffers (the
  functional analogue of the reference's delayed ``return_link_resources`` /
  ``finish_processing`` SimPy processes), the active scheduling/placement
  tensors and the RNG key.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import struct

# Flow phases (flow lifecycle, reference: flowsimulator.py:72-128).
PH_FREE = 0     # slot unused
PH_DECIDE = 1   # at a node, waiting for a next-node decision this substep
PH_HOP = 2      # traversing an edge (timer = remaining hop delay)
PH_PROC = 3     # processing at an SF (timer = startup wait + processing delay)

# Drop reasons (metrics.py:33-38).
DROP_TTL = 0
DROP_DECISION = 1
DROP_LINK_CAP = 2
DROP_NODE_CAP = 3
DROP_REASONS = ("TTL", "DECISION", "LINK_CAP", "NODE_CAP")


@struct.dataclass
class FlowTable:
    """Preallocated flow slots [M] (reference: Flow, flow.py:10-48)."""

    phase: jnp.ndarray      # [M] i32 PH_*
    sfc: jnp.ndarray        # [M] i32
    position: jnp.ndarray   # [M] i32 index into the SFC chain; == chain_len -> to egress
    node: jnp.ndarray       # [M] i32 current node
    dest: jnp.ndarray       # [M] i32 decided destination node (while forwarding)
    hop_next: jnp.ndarray   # [M] i32 node at the end of the in-flight hop
    egress: jnp.ndarray     # [M] i32 egress node id or -1
    dr: jnp.ndarray         # [M] f32 data rate
    duration: jnp.ndarray   # [M] f32 flow duration in ms (= size/dr*1000, flow.py:33)
    ttl: jnp.ndarray        # [M] f32 remaining TTL in ms
    e2e: jnp.ndarray        # [M] f32 accumulated end-to-end delay
    pend_path: jnp.ndarray  # [M] f32 path delay of the in-flight path, credited on arrival
                            #     (the reference adds the whole path delay once after the
                            #     final hop, default_forwarder.py:83-86)
    timer: jnp.ndarray      # [M] f32 remaining time in current phase

    @property
    def active(self) -> jnp.ndarray:
        return self.phase != PH_FREE

    @classmethod
    def empty(cls, max_flows: int) -> "FlowTable":
        zi = jnp.zeros(max_flows, jnp.int32)
        zf = jnp.zeros(max_flows, jnp.float32)
        return cls(phase=zi, sfc=zi, position=zi, node=zi, dest=zi, hop_next=zi,
                   egress=zi - 1, dr=zf, duration=zf, ttl=zf, e2e=zf,
                   pend_path=zf, timer=zf)


@struct.dataclass
class SimMetrics:
    """Counters (reference: metrics.py:22-95).  ``run_*`` fields reset at the
    start of every control interval (writer.py:222-225); the rest accumulate
    over the episode."""

    # cumulative
    generated: jnp.ndarray          # [] i32 (metrics.py:'generated_flows')
    processed: jnp.ndarray          # [] i32
    dropped: jnp.ndarray            # [] i32
    active: jnp.ndarray             # [] i32 ('total_active_flows')
    drop_reasons: jnp.ndarray       # [4] i32 (TTL, DECISION, LINK_CAP, NODE_CAP)
    sum_proc_delay: jnp.ndarray     # [] f32
    num_proc_delay: jnp.ndarray     # [] i32
    sum_path_delay: jnp.ndarray     # [] f32
    num_path_delay: jnp.ndarray     # [] i32
    sum_e2e: jnp.ndarray            # [] f32 (over processed flows)
    # per-run
    run_generated: jnp.ndarray      # [] i32
    run_processed: jnp.ndarray      # [] i32
    run_dropped: jnp.ndarray        # [] i32
    run_dropped_per_node: jnp.ndarray   # [N] i32
    run_e2e_sum: jnp.ndarray        # [] f32
    run_e2e_max: jnp.ndarray        # [] f32
    run_path_delay_sum: jnp.ndarray  # [] f32
    run_requested: jnp.ndarray      # [N,C,S_pos] f32 ('run_total_requested_traffic';
                                    #     indexed by chain POSITION, which maps 1:1
                                    #     to the reference's per-SF-name keying
                                    #     within a chain)
    run_requested_node: jnp.ndarray  # [N] f32 (ingress-generated dr per node)
    run_processed_traffic: jnp.ndarray  # [N,P] f32 (per node per SF id)
    run_flow_counts: jnp.ndarray    # [N,C,S_pos,N] i32 (WRR state, metrics.py:92-95)
    run_max_node_usage: jnp.ndarray  # [N] f32
    run_passed_traffic: jnp.ndarray  # [E] f32 (per-edge, simulatorparams.py:249-257)

    @classmethod
    def zeros(cls, n: int, c: int, s: int, e: int,
              p: int = None) -> "SimMetrics":
        if p is None:
            p = s  # single-chain configs: position axis == id axis
        i = lambda *shape: jnp.zeros(shape, jnp.int32)
        f = lambda *shape: jnp.zeros(shape, jnp.float32)
        return cls(
            generated=i(), processed=i(), dropped=i(), active=i(),
            drop_reasons=i(4), sum_proc_delay=f(), num_proc_delay=i(),
            sum_path_delay=f(), num_path_delay=i(), sum_e2e=f(),
            run_generated=i(), run_processed=i(), run_dropped=i(),
            run_dropped_per_node=i(n), run_e2e_sum=f(), run_e2e_max=f(),
            run_path_delay_sum=f(), run_requested=f(n, c, s),
            run_requested_node=f(n), run_processed_traffic=f(n, p),
            run_flow_counts=i(n, c, s, n), run_max_node_usage=f(n),
            run_passed_traffic=f(e),
        )

    def reset_run(self) -> "SimMetrics":
        """Per-interval reset (reference: metrics.py:64-95 reset_run_metrics,
        fired by the writer process each run_duration, writer.py:222-225)."""
        z = SimMetrics.zeros(self.run_dropped_per_node.shape[0],
                             self.run_requested.shape[1],
                             self.run_requested.shape[2],
                             self.run_passed_traffic.shape[0],
                             p=self.run_processed_traffic.shape[1])
        return self.replace(
            run_generated=z.run_generated, run_processed=z.run_processed,
            run_dropped=z.run_dropped,
            run_dropped_per_node=z.run_dropped_per_node,
            run_e2e_sum=z.run_e2e_sum, run_e2e_max=z.run_e2e_max,
            run_path_delay_sum=z.run_path_delay_sum,
            run_requested=z.run_requested,
            run_requested_node=z.run_requested_node,
            run_processed_traffic=z.run_processed_traffic,
            run_flow_counts=z.run_flow_counts,
            run_max_node_usage=z.run_max_node_usage,
            run_passed_traffic=z.run_passed_traffic,
        )

    def avg_e2e(self) -> jnp.ndarray:
        """'avg_end2end_delay': cumulative e2e over processed flows
        (metrics.py:203-209)."""
        return jnp.where(self.processed > 0,
                         self.sum_e2e / jnp.maximum(self.processed, 1), 0.0)

    def run_avg_e2e(self) -> jnp.ndarray:
        """'run_avg_end2end_delay' (metrics.py:210-215)."""
        return jnp.where(self.run_processed > 0,
                         self.run_e2e_sum / jnp.maximum(self.run_processed, 1), 0.0)


@struct.dataclass
class TrafficSchedule:
    """Pre-generated per-episode traffic, the tensor analogue of the
    reference's per-episode flow lists (simulatorparams.py:185-247) extended
    to cover SFC/egress/TTL choice (default_generator.py:18-60), MMPP state
    switching (simulatorparams.py:143-176) and trace-driven scenario changes
    (trace_processor.py:23-54) — all host-precomputed into dense arrays.

    Flow records are sorted by arrival time; the engine keeps a cursor.
    """

    arr_time: jnp.ndarray     # [F] f32, sorted ascending (inf for padding)
    arr_ingress: jnp.ndarray  # [F] i32
    arr_dr: jnp.ndarray       # [F] f32
    arr_duration: jnp.ndarray  # [F] f32 (size/dr*1000)
    arr_ttl: jnp.ndarray      # [F] f32
    arr_sfc: jnp.ndarray      # [F] i32
    arr_egress: jnp.ndarray   # [F] i32 (-1: none)
    # Per control interval [T, N]: which ingresses generate flows (trace rows
    # can deactivate an ingress, trace_processor.py:37-38; affects placement
    # derivation via get_active_ingress_nodes, siminterface/simulator.py:261-263)
    ingress_active: jnp.ndarray  # [T, N] bool
    # Per control interval node capacity (traces may raise caps mid-episode,
    # trace_processor.py:44-46); row = topology node_cap when unchanged.
    node_cap: jnp.ndarray     # [T, N] f32
    # Per control interval EDGE capacity — the link twin of node_cap, used
    # by mid-episode link-fault scenarios (topology.scenarios): the engine
    # swaps topo.edge_cap for this table's current row at each interval
    # start, entirely inside the scanned episode (no host sync).  None
    # (the default, and every pre-fault producer) keeps the pytree
    # structure — and therefore every compiled program — byte-identical
    # to the fault-unaware stack.
    edge_cap_t: jnp.ndarray = None   # [T, E] f32 or None

    @property
    def capacity(self) -> int:
        return self.arr_time.shape[-1]


@struct.dataclass
class SimState:
    """Complete per-episode mutable simulator state."""

    t: jnp.ndarray            # [] f32 current sim time (ms)
    run_idx: jnp.ndarray      # [] i32 control intervals completed
    flows: FlowTable          # [M] slots
    cursor: jnp.ndarray       # [] i32 next unconsumed traffic-schedule record
    # per (node, SF) bookkeeping (reference 'available_sf' dicts,
    # simulatorparams.py:66-73, duration_controller.py:46-60)
    node_load: jnp.ndarray    # [N,P] f32 current processed load (SF-id axis)
    sf_available: jnp.ndarray  # [N,P] bool placed or still draining
    sf_startup: jnp.ndarray   # [N,P] f32 startup_time of the instance
    sf_last_active: jnp.ndarray  # [N,P] f32 last time the instance had load
                                 #     ('last_active', flow_controller.py:94-112)
    placed: jnp.ndarray       # [N,P] bool current placement action (SF-id axis)
    schedule: jnp.ndarray     # [N,C,S,N] f32 current scheduling weights
    edge_used: jnp.ndarray    # [E] f32 in-flight dr per undirected edge
    # capacity release ring buffers, indexed by substep mod horizon
    rel_node: jnp.ndarray     # [H,N*P] f32 — flat trailing dim: a ragged
                              # [N,P] tail makes XLA layout-copy the whole
                              # ring twice per substep on TPU (~25% of the
                              # measured substep wall at B=512)
    rel_edge: jnp.ndarray     # [H,E] f32
    metrics: SimMetrics
    rng: jnp.ndarray          # PRNG key
    # Arrivals admitted LATER than their scheduled substep because every
    # flow slot (or the per-substep arrival budget) was taken — the
    # engine's visible divergence signal from the reference's unbounded
    # concurrent-flow model.  Each delayed arrival is counted once, when it
    # finally spawns; surfaced by utils.debug.check_invariants.
    truncated_arrivals: jnp.ndarray  # [] i32


def init_state(rng, max_flows: int, n: int, c: int, s: int, e: int,
               horizon: int, p: int = None) -> SimState:
    if p is None:
        p = s
    return SimState(
        t=jnp.zeros((), jnp.float32),
        run_idx=jnp.zeros((), jnp.int32),
        flows=FlowTable.empty(max_flows),
        cursor=jnp.zeros((), jnp.int32),
        node_load=jnp.zeros((n, p), jnp.float32),
        sf_available=jnp.zeros((n, p), bool),
        sf_startup=jnp.zeros((n, p), jnp.float32),
        sf_last_active=jnp.zeros((n, p), jnp.float32),
        placed=jnp.zeros((n, p), bool),
        schedule=jnp.zeros((n, c, s, n), jnp.float32),
        edge_used=jnp.zeros(e, jnp.float32),
        rel_node=jnp.zeros((horizon, n * p), jnp.float32),
        rel_edge=jnp.zeros((horizon, e), jnp.float32),
        metrics=SimMetrics.zeros(n, c, s, e, p=p),
        rng=rng,
        truncated_arrivals=jnp.zeros((), jnp.int32),
    )
