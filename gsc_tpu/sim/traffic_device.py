"""On-device traffic generation — TrafficSchedule sampled entirely in jax.

The host generator (``traffic.py``) is the reference-parity path; this
module is the THROUGHPUT path: per-episode traffic resampling as a jitted
device computation keyed per (replica, episode), so training never ships
MB-scale flow tensors host->device between episodes.  At B=256 on the
flagship scenario the host path moves ~90 MB per episode through the
remote-chip tunnel, which halved sustained training throughput (980 wall
vs 1853 device env-steps/s, BENCH_NOTES r3); host-side SAMPLING is cheap
(~0.5 s/256 traces) — the transfer is the cost being deleted here.

Semantics follow ``traffic.generate_traffic`` / the reference generator
(default_generator.py:18-60, simulatorparams.py:143-247, flowsimulator.py:
59-70):

- per-ingress renewal arrivals: first flow at the start of the node's
  first active interval, then ``t += mean`` (deterministic) or
  ``t += Exp(mean)``; the mean is read from the interval CONTAINING the
  emission time (so MMPP/trace changes apply mid-stream);
- a node whose interval is deactivated (trace ``None``) jumps to the start
  of its next active interval without emitting;
- dr ~ N(mean, stdev) with rejection of negatives — bounded here to 8
  redraws then ``|x|`` (the host loops unboundedly; P(8 rejects) is
  astronomically small for any sane dr config), size deterministic or
  Pareto(shape) with support >= 1, duration = size/dr*1000 ms;
- TTL/SFC/egress uniform choices;
- the global stream is merged sorted by arrival time with the host's
  tie-break (equal times -> lowest node index first).

The MMPP two-state chain (simulatorparams.py:143-176) is sampled on device
per episode; trace-driven mean overrides / deactivations / capacity raises
are DETERMINISTIC per scenario, so they are precomputed host-side once
into [steps, N] tables that live on device across every episode.

The RNG stream necessarily differs from the host generator (jax threefry
vs numpy PCG): a device-sampled episode is distributionally — and, for
fully deterministic configs, bitwise — equivalent, but seeds do not
correspond across the two paths.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import ServiceConfig, SimConfig
from ..topology.compiler import Topology
from .state import TrafficSchedule
from .traffic import TraceEvents, traffic_capacity


def renewal_stream(cfg: SimConfig, means, active, next_active,
                   horizon: float, capacity: int, n_sfcs: int,
                   ttl_choices, eg_table, eg_count: int, key):
    """The renewal merge scan shared by :class:`DeviceTraffic` and the
    on-device scenario factory (:mod:`gsc_tpu.topology.factory`): one
    global arrival stream merged over per-node renewal clocks, semantics
    per the module docstring.  ``means``/``active``/``next_active`` are
    ``[steps, N]`` interval tables (host-precomputed constants for
    DeviceTraffic, traced values conditioned on a sampled topology for
    the factory); ``capacity``/``n_sfcs``/``eg_count`` are static.
    Returns the 7 flow-record arrays of a :class:`TrafficSchedule`
    (times, ingress, dr, duration, ttl, sfc, egress)."""
    steps, n = active.shape
    rd = jnp.float32(cfg.run_duration)

    # first arrival: start of each node's first active interval
    # (flowsimulator.py:63-70 emits at t=0; a trace-deactivated start
    # jumps forward, traffic.py:198-211)
    na0 = next_active[0]
    t_init = jnp.where(na0 < steps, na0.astype(jnp.float32) * rd,
                       jnp.inf)

    node_ids = jnp.arange(n)

    def emit(carry, slot):
        t_next = carry
        ks = jax.random.split(jax.random.fold_in(key, slot), 6)
        t = jnp.min(t_next)
        w = jnp.argmin(t_next)          # ties -> lowest node index,
        oh_w = node_ids == w            # matching the host tie-break
        valid = t < horizon
        kk = jnp.clip((t / rd).astype(jnp.int32), 0, steps - 1)
        mean_w = jnp.where(oh_w, means[kk], 0.0).sum()

        # advance the winner's renewal clock
        gap = jnp.where(cfg.deterministic_arrival, mean_w,
                        mean_w * jax.random.exponential(ks[0]))
        tp = t + gap
        k2 = (tp / rd).astype(jnp.int32)
        ended = (~jnp.isfinite(tp)) | (k2 >= steps)
        k2c = jnp.clip(k2, 0, steps - 1)
        act2 = jnp.where(oh_w, active[k2c], False).any()
        na = jnp.where(oh_w, next_active[k2c], steps).min()
        t_jump = jnp.where(na < steps, na.astype(jnp.float32) * rd,
                           jnp.inf)
        t_new = jnp.where(ended, jnp.inf, jnp.where(act2, tp, t_jump))
        t_next = jnp.where(oh_w, t_new, t_next)

        # flow attributes (default_generator.py:30-60)
        drs = cfg.flow_dr_mean + cfg.flow_dr_stdev * \
            jax.random.normal(ks[1], (8,))
        ok = drs >= 0.0
        dr = jnp.where(ok.any(), drs[jnp.argmax(ok)], jnp.abs(drs[-1]))
        size = jnp.where(cfg.deterministic_size,
                         jnp.float32(cfg.flow_size_shape),
                         jax.random.pareto(
                             ks[2], jnp.float32(cfg.flow_size_shape)))
        dur = jnp.where(dr > 0, size / jnp.maximum(dr, 1e-30) * 1000.0,
                        0.0)
        ttl = ttl_choices[jax.random.randint(
            ks[3], (), 0, ttl_choices.shape[0])]
        sfc = jax.random.randint(ks[4], (), 0, n_sfcs)
        if eg_count:
            eg = eg_table[jax.random.randint(ks[5], (), 0, eg_count)]
        else:
            eg = jnp.int32(-1)
        row = (jnp.where(valid, t, jnp.inf),
               jnp.where(valid, w, 0).astype(jnp.int32),
               jnp.where(valid, dr, 0.0),
               jnp.where(valid, dur, 0.0),
               jnp.where(valid, ttl, 0.0),
               jnp.where(valid, sfc, 0).astype(jnp.int32),
               jnp.where(valid, eg, -1).astype(jnp.int32))
        return t_next, row

    # the merge scan is `capacity` tiny sequential steps (12.8k on the
    # flagship): unrolling amortizes the per-iteration loop overhead,
    # which dominates a body this small on TPU
    _, rows = jax.lax.scan(emit, t_init, jnp.arange(capacity),
                           unroll=8 if capacity % 8 == 0 else 1)
    return rows


class DeviceTraffic:
    """Per-scenario traffic sampler whose ``sample(key)`` is jittable and
    vmappable.  Build once per (config, service, topology, trace); call
    ``sample`` with a fresh key per (replica, episode)."""

    def __init__(self, cfg: SimConfig, service: ServiceConfig,
                 topo: Topology, episode_steps: int,
                 trace: Optional[TraceEvents] = None,
                 capacity: Optional[int] = None,
                 faults=(), with_edge_cap: bool = False):
        n = topo.max_nodes
        steps = episode_steps
        node_cap = np.asarray(topo.node_cap)
        ing_mask = np.asarray(topo.is_ingress) & np.asarray(topo.node_mask)
        eg_idx = np.nonzero(np.asarray(topo.is_egress)
                            & np.asarray(topo.node_mask))[0]
        ing_idx = np.nonzero(ing_mask)[0]

        # ---- deterministic interval tables (host, once per scenario) ----
        caps = np.broadcast_to(node_cap, (steps, n)).copy()
        ovr_mask = np.zeros((steps, n), bool)
        ovr_vals = np.full((steps, n), np.inf, np.float32)
        if trace is not None:
            for (t0, node, mean, cap) in trace.rows:
                k0 = min(int(t0 // cfg.run_duration), steps)
                if node in ing_idx:
                    ovr_mask[k0:, node] = True
                    ovr_vals[k0:, node] = np.inf if mean is None else mean
                if cap is not None:
                    caps[k0:, node] = cap
        # deterministic capacity-fault scenarios (topology.scenarios):
        # node faults fold into the per-interval caps table right here —
        # static per scenario, so episode sampling never re-applies them;
        # link faults build the [T, E] edge table attached to every
        # sampled schedule (with_edge_cap forces it so mixed batches
        # stack structurally even when only some members have one)
        self.edge_cap_t = None
        if faults or with_edge_cap:
            from ..topology.scenarios import apply_faults
            caps, self.edge_cap_t = apply_faults(topo, caps, steps, faults,
                                                 with_edge_cap)
        if cfg.use_states:
            active = np.zeros((steps, n), bool)
            active[:, ing_idx] = True
            base_means = np.full((steps, n), np.inf, np.float32)  # unused
        else:
            base_means = np.full((steps, n), np.inf, np.float32)
            base_means[:, ing_idx] = cfg.inter_arrival_mean
            base_means = np.where(ovr_mask, ovr_vals, base_means)
            active = np.isfinite(base_means)
        active = np.where(ovr_mask, np.isfinite(ovr_vals), active)
        # next_active[k, v] = smallest active interval k' >= k (steps = none)
        nxt = np.full((steps + 1, n), steps, np.int32)
        for k in range(steps - 1, -1, -1):
            nxt[k] = np.where(active[k], k, nxt[k + 1])
        self.cfg = cfg
        self.episode_steps = steps
        self.capacity = capacity if capacity is not None else \
            traffic_capacity(cfg, len(ing_idx), steps)
        self.horizon = float(steps * cfg.run_duration)
        self.n_sfcs = max(len(service.sfc_names), 1)
        # device-resident constants (closed over by the jitted sampler)
        self.base_means = jnp.asarray(base_means)
        self.active = jnp.asarray(active)
        self.next_active = jnp.asarray(nxt[:steps])
        self.caps = jnp.asarray(caps, jnp.float32)
        self.ovr_mask = jnp.asarray(ovr_mask)
        self.ovr_vals = jnp.asarray(ovr_vals)
        self.ing_mask = jnp.asarray(ing_mask)
        self.ttl_choices = jnp.asarray(cfg.ttl_choices, jnp.float32)
        self.eg_table = jnp.asarray(
            np.concatenate([eg_idx, np.zeros(max(n - len(eg_idx), 1),
                                             np.int64)])[:max(n, 1)],
            jnp.int32)
        self.eg_count = int(len(eg_idx))
        if cfg.use_states:
            self.state_means = jnp.asarray(
                [s.inter_arr_mean for s in cfg.states], jnp.float32)
            self.switch_p = jnp.asarray(
                [s.switch_p for s in cfg.states], jnp.float32)
            names = [s.name for s in cfg.states]
            self.init_state = (0 if cfg.init_state is None
                               else names.index(cfg.init_state))

    # ------------------------------------------------------------- sampling
    def _interval_means(self, key) -> jnp.ndarray:
        """[steps, N] per-interval arrival means (inf = inactive)."""
        steps, n = self.active.shape
        if self.cfg.use_states:
            # two-state MMPP chain per ingress: state updates at every
            # run_duration boundary with the current state's switch_p
            # (simulatorparams.py:152-176)
            k_init, k_chain = jax.random.split(key)
            if self.cfg.rand_init_state:
                s0 = jax.random.randint(k_init, (n,), 0, 2)
            else:
                s0 = jnp.full((n,), self.init_state, jnp.int32)

            def step(s, k):
                means_now = jnp.where(s == 0, self.state_means[0],
                                      self.state_means[1])
                sw = jax.random.uniform(k, (n,)) < jnp.where(
                    s == 0, self.switch_p[0], self.switch_p[1])
                return jnp.where(sw, 1 - s, s), means_now

            _, means = jax.lax.scan(step, s0,
                                    jax.random.split(k_chain, steps))
            means = jnp.where(self.ing_mask[None, :], means, jnp.inf)
            means = jnp.where(self.ovr_mask, self.ovr_vals, means)
        else:
            means = self.base_means
        return jnp.where(self.active, means, jnp.inf)

    def sample(self, key) -> TrafficSchedule:
        """One episode of traffic, entirely on device.  jit/vmap freely."""
        k_means, k_flows = jax.random.split(key)
        means = self._interval_means(k_means)
        times, ingress, drs, durs, ttls, sfcs, egs = renewal_stream(
            self.cfg, means, self.active, self.next_active, self.horizon,
            self.capacity, self.n_sfcs, self.ttl_choices, self.eg_table,
            self.eg_count, k_flows)
        return TrafficSchedule(
            arr_time=times, arr_ingress=ingress, arr_dr=drs,
            arr_duration=durs, arr_ttl=ttls, arr_sfc=sfcs, arr_egress=egs,
            ingress_active=self.active, node_cap=self.caps,
            edge_cap_t=self.edge_cap_t)

    def sample_batch(self, key, num_replicas: int) -> TrafficSchedule:
        """[B]-stacked schedules (one per replica), a single device call."""
        return jax.vmap(self.sample)(jax.random.split(key, num_replicas))
