"""Fake simulator backend — the DummySimulator analogue.

Reference: dummy_env/dummy_simulator.py:8-155 implements the simulator
interface with one canned 3-node state so the RL stack can be exercised
without running the simulator (SURVEY.md §4's "mock cluster" pattern).
``DummyEngine`` does the same for the tensor contract: it matches
``SimEngine``'s ``init``/``apply`` signatures and shapes but fabricates
deterministic metrics (10 generated, 8 processed, 2 dropped per interval,
fixed 20 ms average e2e) instead of simulating — jittable, vmappable, and
drop-in for ``ServiceCoordEnv``'s engine.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..config.schema import EnvLimits, ServiceConfig, SimConfig
from ..topology.compiler import Topology
from .engine import ServiceTables, SimEngine
from .state import SimMetrics, SimState, TrafficSchedule, init_state


class DummyEngine(SimEngine):
    """Canned-state fake with the SimEngine contract."""

    GENERATED = 10
    PROCESSED = 8
    DROPPED = 2
    AVG_E2E = 20.0

    @partial(jax.jit, static_argnums=0)
    def apply(self, state: SimState, topo: Topology, traffic: TrafficSchedule,
              schedule: jnp.ndarray, placement: jnp.ndarray
              ) -> Tuple[SimState, SimMetrics]:
        m = state.metrics.reset_run()
        gen = jnp.asarray(self.GENERATED, jnp.int32)
        proc = jnp.asarray(self.PROCESSED, jnp.int32)
        drop = jnp.asarray(self.DROPPED, jnp.int32)
        # spread canned traffic over the real ingress nodes so observations
        # are non-trivial (the reference's canned state carries fixed
        # traffic/load dicts, dummy_simulator.py:51-155)
        ing = (topo.is_ingress & topo.node_mask).astype(jnp.float32)
        req = jnp.zeros_like(m.run_requested)
        for c in range(req.shape[1]):
            req = req.at[:, c, 0].set(ing)  # position-indexed entry point
        proc_traffic = placement.astype(jnp.float32) * 0.5
        m = m.replace(
            generated=m.generated + gen, processed=m.processed + proc,
            dropped=m.dropped + drop,
            drop_reasons=m.drop_reasons.at[3].add(drop),
            run_generated=gen, run_processed=proc, run_dropped=drop,
            run_e2e_sum=jnp.asarray(self.AVG_E2E * self.PROCESSED),
            run_e2e_max=jnp.asarray(self.AVG_E2E),
            sum_e2e=m.sum_e2e + self.AVG_E2E * self.PROCESSED,
            run_requested=req, run_requested_node=ing,
            run_processed_traffic=proc_traffic,
        )
        state = state.replace(
            t=state.t + self.cfg.run_duration,
            run_idx=state.run_idx + 1,
            placed=placement, schedule=schedule, metrics=m,
        )
        return state, m
