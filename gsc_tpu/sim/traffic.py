"""Host-side traffic pre-generation -> TrafficSchedule tensors.

The reference generates flow arrivals *during* simulation: an ``init_arrival``
SimPy process per ingress samples inter-arrival/dr/size inline
(flowsimulator.py:59-70, default_generator.py:18-60) with per-node arrival
means that may change over the episode via the two-state MMPP
(simulatorparams.py:143-176) or a CSV trace (trace_processor.py:23-54).
Data-dependent arrival loops are unmappable to XLA, and the reference itself
already pre-generates per-episode flow lists (simulatorparams.py:185-247) —
we take that idea to its conclusion: the *entire* episode's traffic (arrival
times, rates, sizes, TTLs, SFC/egress choices, per-interval ingress activity
and node-capacity overrides) is sampled host-side with numpy into one dense
sorted ``TrafficSchedule`` that the on-device engine merely consumes.

Distribution semantics preserved:
- deterministic vs Poisson arrivals: inter-arrival = mean or Exp(mean)
  (default_generator.py:21-25); first flow at t=0 (flowsimulator.py:63-70).
- dr ~ Normal(dr_mean, dr_stdev); size = shape (deterministic) or
  Pareto(shape)+1; joint rejection-resampling of negatives
  (default_generator.py:47-60).
- duration = size/dr * 1000 ms (flow.py:33).
- SFC ~ uniform choice; egress ~ uniform choice of egress nodes (or none);
  TTL ~ uniform choice of ttl_choices (default_generator.py:30-40).
- MMPP: per-ingress two-state Markov chain switching with prob switch_p at
  every run_duration boundary; arrival mean follows the current state
  (simulatorparams.py:143-176).  Initial state: init_state, or random per
  node when rand_init_state (simulatorparams.py:108-116).
- trace: rows (time, node, inter_arrival_mean) set a node's arrival mean
  from that time on; 'None' deactivates the ingress; optional cap column
  raises node capacity (trace_processor.py:23-54).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..config.schema import ServiceConfig, SimConfig
from ..topology.compiler import Topology
from .state import TrafficSchedule


def traffic_capacity(cfg: SimConfig, num_ingress: int, episode_steps: int,
                     pad_factor: float = 1.6) -> int:
    """Static upper bound on flows per episode (keeps shapes fixed across
    episodes so nothing recompiles)."""
    horizon = episode_steps * cfg.run_duration
    mean = cfg.inter_arrival_mean
    if cfg.use_states:
        mean = min(s.inter_arr_mean for s in cfg.states)
    expected = horizon / max(mean, 1e-6) * max(num_ingress, 1)
    cap = int(expected * pad_factor) + 8 * max(num_ingress, 1)
    # round up to a multiple of 64 for nicer TPU layouts
    return ((cap + 63) // 64) * 64


class TraceEvents:
    """Parsed trace CSV (reference format: time,node,inter_arrival_mean[,cap]
    — configs/traces/*.csv, trace_processor.py:29-46)."""

    def __init__(self, rows: Sequence[Tuple[float, int, Optional[float], Optional[float]]]):
        # each row: (time, node_index, inter_arrival_mean or None, cap or None)
        self.rows = sorted(rows, key=lambda r: r[0])

    @classmethod
    def from_csv(cls, path: str, node_name_to_idx) -> "TraceEvents":
        import csv

        rows = []
        with open(path) as f:
            for rec in csv.DictReader(f):
                t = float(rec["time"])
                node = rec["node"]
                idx = node_name_to_idx(node)
                mean_raw = rec.get("inter_arrival_mean")
                mean = (None if mean_raw in (None, "", "None") else float(mean_raw))
                cap = rec.get("cap")
                cap = None if cap in (None, "", "None") else float(cap)
                rows.append((t, idx, mean, cap))
        return cls(rows)


def _mmpp_interval_means(cfg: SimConfig, ing_idx: np.ndarray, steps: int,
                         rng: np.random.Generator) -> np.ndarray:
    """Per-(interval, ingress) arrival means from the two-state MMPP chain.
    State updates happen at every run_duration boundary with switch
    probability of the current state (simulatorparams.py:152-176)."""
    names = [s.name for s in cfg.states]
    means = {s.name: s.inter_arr_mean for s in cfg.states}
    switch = {s.name: s.switch_p for s in cfg.states}
    n_ing = len(ing_idx)
    if cfg.rand_init_state:
        cur = [names[rng.integers(len(names))] for _ in range(n_ing)]
    else:
        cur = [cfg.init_state] * n_ing
    out = np.zeros((steps, n_ing), np.float64)
    for t in range(steps):
        out[t] = [means[c] for c in cur]
        # switch decision at the end of the interval (start_mmpp waits one
        # run_duration before the first update, simulatorparams.py:146-151)
        cur = [
            (names[1 - names.index(c)] if rng.random() < switch[c] else c)
            for c in cur
        ]
    return out


def generate_traffic(
    cfg: SimConfig,
    service: ServiceConfig,
    topo: Topology,
    episode_steps: int,
    seed: int,
    trace: Optional[TraceEvents] = None,
    capacity: Optional[int] = None,
    faults: Sequence = (),
    with_edge_cap: bool = False,
) -> TrafficSchedule:
    """Sample one episode of traffic into a TrafficSchedule.

    ``faults`` (topology.scenarios.TopoFault sequence): deterministic
    mid-episode capacity faults — node faults zero rows of the
    per-interval ``node_cap`` table, link faults materialize (and zero
    rows of) the per-interval ``edge_cap_t`` table the engine
    row-selects.  ``with_edge_cap`` forces ``edge_cap_t`` even without a
    link fault, so a mixed batch where only SOME members have link
    faults still stacks into one consistent pytree structure."""
    rng = np.random.default_rng(seed)
    n = topo.max_nodes
    node_cap = np.asarray(topo.node_cap)
    ing_mask = np.asarray(topo.is_ingress) & np.asarray(topo.node_mask)
    eg_idx = np.nonzero(np.asarray(topo.is_egress) & np.asarray(topo.node_mask))[0]
    ing_idx = np.nonzero(ing_mask)[0]
    sfc_ids = np.arange(len(service.sfc_names))
    horizon = episode_steps * cfg.run_duration

    # --- per-(interval, node) arrival means & activity -----------------------
    means = np.full((episode_steps, n), np.nan)
    if cfg.use_states and len(ing_idx):
        means[:, ing_idx] = _mmpp_interval_means(cfg, ing_idx, episode_steps, rng)
    else:
        means[:, ing_idx] = cfg.inter_arrival_mean
    caps = np.broadcast_to(node_cap, (episode_steps, n)).copy()
    if trace is not None:
        for (t0, node, mean, cap) in trace.rows:
            k0 = min(int(t0 // cfg.run_duration), episode_steps)
            if node in ing_idx:
                means[k0:, node] = np.nan if mean is None else mean
            if cap is not None:
                caps[k0:, node] = cap
    active = ~np.isnan(means)

    edge_cap_t = None
    if faults or with_edge_cap:
        from ..topology.scenarios import apply_faults
        caps, edge_cap_t = apply_faults(topo, caps, episode_steps, faults,
                                        with_edge_cap)

    cap_f = capacity if capacity is not None else traffic_capacity(
        cfg, len(ing_idx), episode_steps)

    # --- flow records: native C++ sampler when available ---------------------
    from ..native import generate_flows_native

    native = generate_flows_native(
        seed=seed, means=means, run_duration=cfg.run_duration,
        dr_mean=cfg.flow_dr_mean, dr_stdev=cfg.flow_dr_stdev,
        size_shape=cfg.flow_size_shape,
        det_arrival=cfg.deterministic_arrival, det_size=cfg.deterministic_size,
        ttl_choices=np.asarray(cfg.ttl_choices), n_sfcs=len(sfc_ids),
        egress_nodes=eg_idx, capacity=cap_f)
    if native is not None:
        n_times, n_ing, n_drs, n_durs, n_ttls, n_sfcs_a, n_egs = native

        def pad_native(vals, fill, dtype):
            out = np.full(cap_f, fill, dtype)
            out[:len(vals)] = np.asarray(vals, dtype)
            return out

        return TrafficSchedule(
            arr_time=jnp.asarray(pad_native(n_times, np.inf, np.float32)),
            arr_ingress=jnp.asarray(pad_native(n_ing, 0, np.int32)),
            arr_dr=jnp.asarray(pad_native(n_drs, 0.0, np.float32)),
            arr_duration=jnp.asarray(pad_native(n_durs, 0.0, np.float32)),
            arr_ttl=jnp.asarray(pad_native(n_ttls, 0.0, np.float32)),
            arr_sfc=jnp.asarray(pad_native(n_sfcs_a, 0, np.int32)),
            arr_egress=jnp.asarray(pad_native(n_egs, -1, np.int32)),
            ingress_active=jnp.asarray(active),
            node_cap=jnp.asarray(caps, np.float32),
            edge_cap_t=edge_cap_t,
        )

    # --- numpy fallback ------------------------------------------------------
    times: List[float] = []
    ingress: List[int] = []
    drs: List[float] = []
    durs: List[float] = []
    ttls: List[float] = []
    sfcs: List[int] = []
    egs: List[int] = []

    def sample_dr_size() -> Tuple[float, float]:
        # joint rejection-resample (default_generator.py:47-60)
        while True:
            dr = rng.normal(cfg.flow_dr_mean, cfg.flow_dr_stdev)
            if cfg.deterministic_size:
                size = cfg.flow_size_shape
            else:
                size = rng.pareto(cfg.flow_size_shape) + 1
            if dr >= 0.0 and size >= 0.0:
                return float(dr), float(size)

    for node in ing_idx:
        t = 0.0
        while t < horizon:
            k = int(t // cfg.run_duration)
            mean = means[k, node]
            if math.isnan(mean):
                # ingress deactivated: jump to the next interval where a trace
                # row might reactivate it (arrival loop stops on None,
                # flowsimulator.py:63; only a later trace row restarts it)
                nxt = np.nonzero(active[k:, node])[0]
                if len(nxt) == 0:
                    break
                t = float((k + nxt[0]) * cfg.run_duration)
                continue
            # flow generated first, then inter-arrival sleep
            # (flowsimulator.py:63-70): first arrival at t
            dr, size = sample_dr_size()
            dur = (size / dr) * 1000.0 if dr > 0 else 0.0
            times.append(t)
            ingress.append(int(node))
            drs.append(dr)
            durs.append(dur)
            ttls.append(float(cfg.ttl_choices[rng.integers(len(cfg.ttl_choices))]))
            sfcs.append(int(sfc_ids[rng.integers(len(sfc_ids))]))
            egs.append(int(eg_idx[rng.integers(len(eg_idx))]) if len(eg_idx) else -1)
            if cfg.deterministic_arrival:
                t += mean
            else:
                t += rng.exponential(mean)

    order = np.argsort(np.asarray(times, np.float64), kind="stable")
    f = len(order)
    if f > cap_f:  # should not happen with the default pad factor
        order = order[:cap_f]
        f = cap_f

    def pad_f(vals, fill, dtype):
        out = np.full(cap_f, fill, dtype)
        if f:
            out[:f] = np.asarray(vals, dtype)[order]
        return out

    return TrafficSchedule(
        arr_time=jnp.asarray(pad_f(times, np.inf, np.float32)),
        arr_ingress=jnp.asarray(pad_f(ingress, 0, np.int32)),
        arr_dr=jnp.asarray(pad_f(drs, 0.0, np.float32)),
        arr_duration=jnp.asarray(pad_f(durs, 0.0, np.float32)),
        arr_ttl=jnp.asarray(pad_f(ttls, 0.0, np.float32)),
        arr_sfc=jnp.asarray(pad_f(sfcs, 0, np.int32)),
        arr_egress=jnp.asarray(pad_f(egs, -1, np.int32)),
        ingress_active=jnp.asarray(active),
        node_cap=jnp.asarray(caps, np.float32),
        edge_cap_t=edge_cap_t,
    )
