"""TPU-native batched flow simulator (replaces coordsim's SimPy core)."""
from .state import (  # noqa: F401
    DROP_DECISION,
    DROP_LINK_CAP,
    DROP_NODE_CAP,
    DROP_REASONS,
    DROP_TTL,
    FlowTable,
    SimMetrics,
    SimState,
    TrafficSchedule,
)
from .engine import ServiceTables, SimEngine  # noqa: F401
from .traffic import TraceEvents, generate_traffic, traffic_capacity  # noqa: F401
from .traffic_device import DeviceTraffic  # noqa: F401
from .perflow import PendingFlows, PerFlowController  # noqa: F401
from .dummy import DummyEngine  # noqa: F401
from .predictor import (  # noqa: F401
    RNNTrafficPredictor,
    interval_traffic_series,
    predict_ingress_traffic,
)
