"""Fused GATv2 attention — Pallas TPU kernel.

One kernel fuses the whole attention stage of a GATv2 layer — pairwise
LeakyReLU features, attention logits, masked softmax, weighted aggregation —
for a tile of graphs at a time, keeping the [TB, N, N, F] pairwise
intermediate in VMEM instead of materializing it in HBM (the XLA fallback
``gnn.gatv2_dense`` builds that tensor explicitly).  For replay-buffer-sized
batches (B=100 graphs of 24 padded nodes, sample_agent.yaml) the intermediate
is ~100*24*24*22*4B ≈ 5 MB per layer invocation; fusing it away makes the
layer HBM-bound only on x/out.

Inputs are the already-projected source/target features (the projections are
plain matmuls that XLA maps to the MXU on its own):
    xl = x @ W_l + b_l, xr = x @ W_r + b_r      (see gnn.GATv2Conv)

Grid: one program per tile of TB graphs; each program computes attention for
its whole [TB, N, N] block.  N is the padded MAX_NODES (default 24), so a
tile easily fits VMEM; TB trades VMEM for grid overhead.

On CPU (tests, virtual meshes) the kernel runs in interpret mode and is
bit-compared against ``gatv2_dense`` (tests/test_models.py).

Mixed precision: the kernel is dtype-polymorphic over its xl/xr inputs.
With bf16 projected features (PrecisionPolicy "bf16") the pairwise
[TB, N, N, F] intermediate and both MXU operand sets live in bf16 —
HALVING the VMEM per tile, so the default graph tile TB doubles — while
the attention logits and the masked softmax accumulate in f32
(``preferred_element_type`` on both contractions) and the result rounds
once to bf16 at the output write.  Every cast is a no-op for f32 inputs,
so the f32 kernel is unchanged.  The bf16 kernel is parity-tested against
the bf16 branch of ``ops.gat.attention_dense`` in interpret mode
(tests/test_precision.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .gat import LEAKY_SLOPE, NEG_INF


def _gat_kernel(xl_ref, xr_ref, att_ref, bias_ref, adj_ref, out_ref, *,
                mean_aggr: bool):
    xl = xl_ref[...]          # [TB, N, F]
    xr = xr_ref[...]
    att = att_ref[...]        # [F]
    bias = bias_ref[...]      # [F]
    adj = adj_ref[...]        # [TB, N, N] bool

    # dtype-polymorphic: every cast below is a no-op for f32 inputs; for
    # bf16 the [TB, i, j, F] intermediate and both dot operand sets stay
    # bf16 while logits/softmax/accumulators run f32 (preferred_element_
    # type) — the same op sequence as attention_dense's bf16 branch
    e = xl[:, None, :, :] + xr[:, :, None, :]          # [TB, i, j, F]
    e = jnp.where(e >= 0, e, LEAKY_SLOPE * e)
    logits = jax.lax.dot_general(
        e, att.astype(e.dtype), (((3,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [TB, i, j] f32
    logits = jnp.where(adj, logits, NEG_INF)
    mx = logits.max(axis=-1, keepdims=True)
    ex = jnp.where(adj, jnp.exp(logits - mx), 0.0)
    denom = ex.sum(axis=-1, keepdims=True)
    alpha = (ex / jnp.maximum(denom, 1e-30)).astype(xl.dtype)  # [TB, i, j]
    out = jax.lax.dot_general(
        alpha, xl, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)            # [TB, i, F] f32
    if mean_aggr:
        deg = adj.sum(axis=-1, keepdims=True)
        out = out / jnp.maximum(deg, 1)
    has_nbr = adj.any(axis=-1, keepdims=True)
    out_ref[...] = jnp.where(has_nbr, out + bias, 0.0).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("mean_aggr", "tile_b", "interpret"))
def _gatv2_pallas_impl(xl: jnp.ndarray, xr: jnp.ndarray, att: jnp.ndarray,
                       bias: jnp.ndarray, adj: jnp.ndarray,
                       mean_aggr: bool = True, tile_b: int | None = None,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Fused attention stage.  xl/xr: [..., N, F] projected features,
    adj: [..., N, N] bool.  Leading dims are flattened into the graph batch;
    a single graph (no leading dim) is supported too.  ``tile_b=None``
    sizes the graph tile by the input dtype: 8 for f32, 16 for 2-byte
    dtypes (the bf16 tile holds the same VMEM bytes as the f32 one)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile_b is None:
        tile_b = 16 if jnp.dtype(xl.dtype).itemsize == 2 else 8
    lead = xl.shape[:-2]
    n, f = xl.shape[-2:]
    b = 1
    for d in lead:
        b *= d
    xl3 = xl.reshape(b, n, f)
    xr3 = xr.reshape(b, n, f)
    adj3 = adj.reshape(b, n, n)
    pad = (-b) % tile_b
    if pad:
        xl3 = jnp.pad(xl3, ((0, pad), (0, 0), (0, 0)))
        xr3 = jnp.pad(xr3, ((0, pad), (0, 0), (0, 0)))
        adj3 = jnp.pad(adj3, ((0, pad), (0, 0), (0, 0)))
    bp = b + pad

    out = pl.pallas_call(
        functools.partial(_gat_kernel, mean_aggr=mean_aggr),
        grid=(bp // tile_b,),
        in_specs=[
            pl.BlockSpec((tile_b, n, f), lambda i: (i, 0, 0)),
            pl.BlockSpec((tile_b, n, f), lambda i: (i, 0, 0)),
            pl.BlockSpec((f,), lambda i: (0,)),
            pl.BlockSpec((f,), lambda i: (0,)),
            pl.BlockSpec((tile_b, n, n), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_b, n, f), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, n, f), xl.dtype),
        interpret=interpret,
    )(xl3, xr3, att, bias, adj3)
    return out[:b].reshape(*lead, n, f)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def gatv2_pallas(xl: jnp.ndarray, xr: jnp.ndarray, att: jnp.ndarray,
                 bias: jnp.ndarray, adj: jnp.ndarray, mean_aggr: bool = True,
                 tile_b: int | None = None,
                 interpret: bool | None = None) -> jnp.ndarray:
    """Fused attention stage with a custom VJP.

    Pallas kernels define no autodiff rule, so without this the learn
    path (actor/critic gradients through the GNN) cannot use
    ``gnn_impl="pallas"`` at all.  Forward runs the fused kernel;
    backward differentiates the mathematically identical dense
    formulation (``ops.gat.attention_dense`` — the bit-parity reference
    this kernel is tested against), so gradients equal the dense path's
    exactly while the forward still skips the [B, N, N, F] HBM
    intermediate.  ``attention_dense`` keys its precision on the saved
    residuals' dtype, so bf16 forwards get the matching bf16 backward
    with f32 accumulation — no extra plumbing."""
    return _gatv2_pallas_impl(xl, xr, att, bias, adj, mean_aggr, tile_b,
                              interpret)


def _gatv2_pallas_fwd(xl, xr, att, bias, adj, mean_aggr, tile_b, interpret):
    out = _gatv2_pallas_impl(xl, xr, att, bias, adj, mean_aggr, tile_b,
                             interpret)
    return out, (xl, xr, att, bias, adj)


def _gatv2_pallas_bwd(mean_aggr, tile_b, interpret, res, g):
    import numpy as np

    from .gat import attention_dense

    xl, xr, att, bias, adj = res
    _, vjp = jax.vjp(
        lambda xl_, xr_, att_, bias_: attention_dense(
            xl_, xr_, att_, bias_, adj, mean_aggr), xl, xr, att, bias)
    d_xl, d_xr, d_att, d_bias = vjp(g)
    d_adj = np.zeros(adj.shape, dtype=jax.dtypes.float0)  # bool primal
    return d_xl, d_xr, d_att, d_bias, d_adj


gatv2_pallas.defvjp(_gatv2_pallas_fwd, _gatv2_pallas_bwd)
