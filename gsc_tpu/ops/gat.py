"""GATv2 attention math — pure-function XLA implementations.

These are the kernel-level primitives behind ``gsc_tpu.models.gnn``: dense
masked attention (the default XLA path) and the edge-list segment-sum
formulation (numerically identical to torch-geometric's sparse computation,
used for parity tests).  The fused Pallas TPU kernel lives in
``gsc_tpu.ops.pallas_gat`` and is parity-tested against ``gatv2_dense``.

GATv2 math per directed edge j->i (torch_geometric GATv2Conv semantics,
reference usage at src/rlsp/agents/models.py:22-27):
    e_ij   = a^T LeakyReLU_0.2(W_l x_j + W_r x_i)
    alpha  = softmax_j(e_ij) over in-neighbors (self-loop included)
    out_i  = aggr_j(alpha_ij * W_l x_j) + b      (aggr: sum or mean)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30
LEAKY_SLOPE = 0.2


def dense_adj(edge_index: jnp.ndarray, edge_mask: jnp.ndarray,
              node_mask: jnp.ndarray) -> jnp.ndarray:
    """Directed edge list -> dense [N, N] bool adjacency ``adj[i, j]`` = "j is
    an in-neighbor of i", with self-loops on real nodes (GATv2Conv's
    add_self_loops default).  Leading batch dims supported via vmap."""
    def one(ei, em, nm):
        n = nm.shape[0]
        adj = jnp.zeros((n, n), bool)
        src, dst = ei[0], ei[1]
        adj = adj.at[jnp.where(em, dst, n), jnp.where(em, src, n)].set(
            True, mode="drop")
        return adj | (jnp.eye(n, dtype=bool) & nm[:, None])

    for _ in range(edge_index.ndim - 2):
        one = jax.vmap(one)
    return one(edge_index, edge_mask, node_mask)


def attention_dense(xl: jnp.ndarray, xr: jnp.ndarray, att: jnp.ndarray,
                    bias: jnp.ndarray, adj: jnp.ndarray,
                    mean_aggr: bool) -> jnp.ndarray:
    """The attention STAGE on already-projected features (xl/xr:
    [..., N, F]) — the math the Pallas kernel fuses, and the backward pass
    it borrows (pallas_gat.py defines the kernel's custom VJP through this
    function)."""
    e = xl[..., None, :, :] + xr[..., :, None, :]   # [..., i, j, F]
    e = jnp.where(e >= 0, e, LEAKY_SLOPE * e)
    logits = jnp.einsum("...ijf,f->...ij", e, att)
    logits = jnp.where(adj, logits, NEG_INF)
    mx = jax.lax.stop_gradient(logits.max(axis=-1, keepdims=True))
    ex = jnp.where(adj, jnp.exp(logits - mx), 0.0)
    denom = ex.sum(axis=-1, keepdims=True)
    alpha = ex / jnp.maximum(denom, 1e-30)
    out = jnp.einsum("...ij,...jf->...if", alpha, xl)
    if mean_aggr:
        deg = adj.sum(axis=-1, keepdims=True)
        out = out / jnp.maximum(deg, 1)
    has_nbr = adj.any(axis=-1, keepdims=True)
    return jnp.where(has_nbr, out + bias, 0.0)


def gatv2_dense(x: jnp.ndarray, adj: jnp.ndarray, w_l: jnp.ndarray,
                b_l: jnp.ndarray, w_r: jnp.ndarray, b_r: jnp.ndarray,
                att: jnp.ndarray, bias: jnp.ndarray,
                mean_aggr: bool) -> jnp.ndarray:
    """Dense masked GATv2 layer.  x: [..., N, F_in], adj: [..., N, N] bool."""
    xl = x @ w_l + b_l                       # [..., N, F] source projection
    xr = x @ w_r + b_r                       # [..., N, F] target projection
    return attention_dense(xl, xr, att, bias, adj, mean_aggr)


def gatv2_segment(x: jnp.ndarray, edge_index: jnp.ndarray,
                  edge_mask: jnp.ndarray, node_mask: jnp.ndarray,
                  w_l: jnp.ndarray, b_l: jnp.ndarray, w_r: jnp.ndarray,
                  b_r: jnp.ndarray, att: jnp.ndarray, bias: jnp.ndarray,
                  mean_aggr: bool) -> jnp.ndarray:
    """Edge-list segment-sum GATv2 (torch-geometric's sparse formulation),
    single graph: x [N, F_in], edge_index [2, E].  Self-loops appended for
    real nodes."""
    n = x.shape[0]
    xl = x @ w_l + b_l
    xr = x @ w_r + b_r
    loops = jnp.arange(n)
    # drop any self-loops already present, then append exactly one per real
    # node (torch-geometric removes and re-adds; the dense path dedups via
    # the bool adjacency)
    src = jnp.concatenate([edge_index[0], loops])
    dst = jnp.concatenate([edge_index[1], loops])
    em = jnp.concatenate([edge_mask & (edge_index[0] != edge_index[1]),
                          node_mask])
    e = xl[src] + xr[dst]
    e = jnp.where(e >= 0, e, LEAKY_SLOPE * e)
    logits = jnp.where(em, e @ att, NEG_INF)
    seg_max = jax.ops.segment_max(logits, dst, num_segments=n)
    seg_max = jax.lax.stop_gradient(
        jnp.where(jnp.isfinite(seg_max), seg_max, 0.0))
    ex = jnp.where(em, jnp.exp(logits - seg_max[dst]), 0.0)
    denom = jax.ops.segment_sum(ex, dst, num_segments=n)
    alpha = ex / jnp.maximum(denom[dst], 1e-30)
    out = jax.ops.segment_sum(alpha[:, None] * xl[src], dst, num_segments=n)
    if mean_aggr:
        deg = jax.ops.segment_sum(em.astype(x.dtype), dst, num_segments=n)
        out = out / jnp.maximum(deg[:, None], 1)
    has_nbr = jax.ops.segment_max(em.astype(jnp.int32), dst, num_segments=n) > 0
    return jnp.where(has_nbr[:, None], out + bias, 0.0)
