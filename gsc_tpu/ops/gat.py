"""GATv2 attention math — pure-function XLA implementations.

These are the kernel-level primitives behind ``gsc_tpu.models.gnn``: dense
masked attention (the default XLA path) and the edge-list segment-sum
formulation (numerically identical to torch-geometric's sparse computation,
used for parity tests).  The fused Pallas TPU kernel lives in
``gsc_tpu.ops.pallas_gat`` and is parity-tested against ``gatv2_dense``.

GATv2 math per directed edge j->i (torch_geometric GATv2Conv semantics,
reference usage at src/rlsp/agents/models.py:22-27):
    e_ij   = a^T LeakyReLU_0.2(W_l x_j + W_r x_i)
    alpha  = softmax_j(e_ij) over in-neighbors (self-loop included)
    out_i  = aggr_j(alpha_ij * W_l x_j) + b      (aggr: sum or mean)

Mixed precision (config.schema.PrecisionPolicy): every entry point takes a
``compute_dtype`` — ``None`` runs the original float32 code VERBATIM
(bit-identical to the dtype-unaware stack); ``"bfloat16"`` keeps the big
pairwise [.., N, N, F] intermediate and the matmul operands in bf16 while
the attention logits, softmax and all contraction ACCUMULATORS stay f32
(``preferred_element_type``).  ``attention_dense`` keys the branch on its
input dtype so the Pallas kernel's custom VJP (which differentiates through
it) follows the forward's precision automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30
LEAKY_SLOPE = 0.2


def project(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
            compute_dtype: str | None = None) -> jnp.ndarray:
    """``x @ w + b`` under the precision policy.  ``None``: the original
    f32 expression, bit-identical.  Low precision: operands cast to the
    compute dtype, the matmul accumulates f32 on the MXU
    (``preferred_element_type``), and the activation settles back to the
    compute dtype."""
    if compute_dtype is None:
        return x @ w + b
    cd = jnp.dtype(compute_dtype)
    xc = x.astype(cd)
    y = jax.lax.dot_general(
        xc, w.astype(cd), (((xc.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (y + b).astype(cd)


def dense_adj(edge_index: jnp.ndarray, edge_mask: jnp.ndarray,
              node_mask: jnp.ndarray) -> jnp.ndarray:
    """Directed edge list -> dense [N, N] bool adjacency ``adj[i, j]`` = "j is
    an in-neighbor of i", with self-loops on real nodes (GATv2Conv's
    add_self_loops default).  Leading batch dims supported via vmap."""
    def one(ei, em, nm):
        n = nm.shape[0]
        adj = jnp.zeros((n, n), bool)
        src, dst = ei[0], ei[1]
        adj = adj.at[jnp.where(em, dst, n), jnp.where(em, src, n)].set(
            True, mode="drop")
        return adj | (jnp.eye(n, dtype=bool) & nm[:, None])

    for _ in range(edge_index.ndim - 2):
        one = jax.vmap(one)
    return one(edge_index, edge_mask, node_mask)


def attention_dense(xl: jnp.ndarray, xr: jnp.ndarray, att: jnp.ndarray,
                    bias: jnp.ndarray, adj: jnp.ndarray,
                    mean_aggr: bool) -> jnp.ndarray:
    """The attention STAGE on already-projected features (xl/xr:
    [..., N, F]) — the math the Pallas kernel fuses, and the backward pass
    it borrows (pallas_gat.py defines the kernel's custom VJP through this
    function).

    Precision follows ``xl.dtype``: float32 inputs take the original code
    path verbatim; low-precision inputs (bf16) keep the [.., i, j, F]
    pairwise tensor and both matmul operand sets in that dtype with f32
    logits/softmax/accumulators, and return in the input dtype — the same
    op sequence the bf16 Pallas kernel fuses, so interpret-mode parity
    holds bit-for-bit."""
    if xl.dtype == jnp.float32:
        e = xl[..., None, :, :] + xr[..., :, None, :]   # [..., i, j, F]
        e = jnp.where(e >= 0, e, LEAKY_SLOPE * e)
        logits = jnp.einsum("...ijf,f->...ij", e, att)
        logits = jnp.where(adj, logits, NEG_INF)
        mx = jax.lax.stop_gradient(logits.max(axis=-1, keepdims=True))
        ex = jnp.where(adj, jnp.exp(logits - mx), 0.0)
        denom = ex.sum(axis=-1, keepdims=True)
        alpha = ex / jnp.maximum(denom, 1e-30)
        out = jnp.einsum("...ij,...jf->...if", alpha, xl)
        if mean_aggr:
            deg = adj.sum(axis=-1, keepdims=True)
            out = out / jnp.maximum(deg, 1)
        has_nbr = adj.any(axis=-1, keepdims=True)
        return jnp.where(has_nbr, out + bias, 0.0)
    cd = xl.dtype
    e = xl[..., None, :, :] + xr[..., :, None, :]       # [..., i, j, F] bf16
    e = jnp.where(e >= 0, e, LEAKY_SLOPE * e)
    logits = jnp.einsum("...ijf,f->...ij", e, att.astype(cd),
                        preferred_element_type=jnp.float32)
    logits = jnp.where(adj, logits, NEG_INF)            # f32 logits
    mx = jax.lax.stop_gradient(logits.max(axis=-1, keepdims=True))
    ex = jnp.where(adj, jnp.exp(logits - mx), 0.0)      # f32 softmax
    denom = ex.sum(axis=-1, keepdims=True)
    alpha = (ex / jnp.maximum(denom, 1e-30)).astype(cd)
    out = jnp.einsum("...ij,...jf->...if", alpha, xl,
                     preferred_element_type=jnp.float32)
    if mean_aggr:
        deg = adj.sum(axis=-1, keepdims=True)
        out = out / jnp.maximum(deg, 1)
    has_nbr = adj.any(axis=-1, keepdims=True)
    return jnp.where(has_nbr, out + bias, 0.0).astype(cd)


def gatv2_dense(x: jnp.ndarray, adj: jnp.ndarray, w_l: jnp.ndarray,
                b_l: jnp.ndarray, w_r: jnp.ndarray, b_r: jnp.ndarray,
                att: jnp.ndarray, bias: jnp.ndarray,
                mean_aggr: bool,
                compute_dtype: str | None = None) -> jnp.ndarray:
    """Dense masked GATv2 layer.  x: [..., N, F_in], adj: [..., N, N] bool.
    ``compute_dtype`` (PrecisionPolicy.gnn_compute) selects the attention
    precision; None is the exact f32 path."""
    xl = project(x, w_l, b_l, compute_dtype)  # [..., N, F] source projection
    xr = project(x, w_r, b_r, compute_dtype)  # [..., N, F] target projection
    return attention_dense(xl, xr, att, bias, adj, mean_aggr)


def gatv2_segment(x: jnp.ndarray, edge_index: jnp.ndarray,
                  edge_mask: jnp.ndarray, node_mask: jnp.ndarray,
                  w_l: jnp.ndarray, b_l: jnp.ndarray, w_r: jnp.ndarray,
                  b_r: jnp.ndarray, att: jnp.ndarray, bias: jnp.ndarray,
                  mean_aggr: bool,
                  compute_dtype: str | None = None) -> jnp.ndarray:
    """Edge-list segment-sum GATv2 (torch-geometric's sparse formulation),
    single graph: x [N, F_in], edge_index [2, E].  Self-loops appended for
    real nodes.  With ``compute_dtype`` the per-edge features stay in the
    compute dtype while logits, softmax and the segment-sum aggregation
    accumulate f32 (segment sums of a bf16*f32 product promote to f32)."""
    n = x.shape[0]
    xl = project(x, w_l, b_l, compute_dtype)
    xr = project(x, w_r, b_r, compute_dtype)
    loops = jnp.arange(n)
    # drop any self-loops already present, then append exactly one per real
    # node (torch-geometric removes and re-adds; the dense path dedups via
    # the bool adjacency)
    src = jnp.concatenate([edge_index[0], loops])
    dst = jnp.concatenate([edge_index[1], loops])
    em = jnp.concatenate([edge_mask & (edge_index[0] != edge_index[1]),
                          node_mask])
    e = xl[src] + xr[dst]
    e = jnp.where(e >= 0, e, LEAKY_SLOPE * e)
    if compute_dtype is None:
        logits = jnp.where(em, e @ att, NEG_INF)
    else:
        logits = jnp.where(
            em, jnp.einsum("ef,f->e", e, att.astype(e.dtype),
                           preferred_element_type=jnp.float32), NEG_INF)
    seg_max = jax.ops.segment_max(logits, dst, num_segments=n)
    seg_max = jax.lax.stop_gradient(
        jnp.where(jnp.isfinite(seg_max), seg_max, 0.0))
    ex = jnp.where(em, jnp.exp(logits - seg_max[dst]), 0.0)
    denom = jax.ops.segment_sum(ex, dst, num_segments=n)
    alpha = ex / jnp.maximum(denom[dst], 1e-30)
    out = jax.ops.segment_sum(alpha[:, None] * xl[src], dst, num_segments=n)
    if mean_aggr:
        deg = jax.ops.segment_sum(em.astype(out.dtype), dst, num_segments=n)
        out = out / jnp.maximum(deg[:, None], 1)
    has_nbr = jax.ops.segment_max(em.astype(jnp.int32), dst, num_segments=n) > 0
    out = jnp.where(has_nbr[:, None], out + bias, 0.0)
    return out if compute_dtype is None else out.astype(compute_dtype)
