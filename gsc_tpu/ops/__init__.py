"""TPU kernel-level ops: XLA reference implementations + Pallas kernels."""
from .gat import (LEAKY_SLOPE, NEG_INF, dense_adj, gatv2_dense,
                  gatv2_segment, project)
from .pallas_gat import gatv2_pallas
from .pallas_substep import substep_megakernel

__all__ = ["LEAKY_SLOPE", "NEG_INF", "dense_adj", "gatv2_dense",
           "gatv2_segment", "gatv2_pallas", "project",
           "substep_megakernel"]
