"""Substep megakernel — the whole ``SimEngine._substep`` as ONE Pallas call.

The round-5 MFU/roofline table proved the substep regime decisively: a
chain of ~60 small fusions at ~30 µs apiece, ~100x above the HBM roof and
~10,000x above the MXU roof — op COUNT, never arithmetic, is the cost.
The XLA engine already fights that with the one-hot idiom (gathers and
scatters as MXU contractions so XLA fuses them); this module takes the
same lesson one level deeper and collapses the entire admission/release
chain — the one-hot contraction + packed-scatter + run-starts pipeline of
``gsc_tpu/sim/engine.py`` — into a single kernel invocation per substep,
selected by ``SimConfig.substep_impl = "pallas"`` (mirroring the
``gnn_impl`` switch and the ``ops/pallas_gat.py`` template; the engine is
not differentiated, so unlike the GAT kernel no custom VJP is needed).

Bit-exactness contract (the ``pytest -m megakernel`` parity suite pins it
against the XLA engine on the reference-parity scenarios):

- pure DATA-MOVEMENT one-hot dots — row lookups (``_take``/``_pick``),
  permutation matmuls, transpose-scatters — are replaced by native
  gathers/scatters.  Each such dot has exactly ONE nonzero term per
  output (1.0 * x plus exact zeros), so the gather produces the same
  VALUE; out-of-range "drop" rows map to ``mode="fill"`` gathers /
  ``mode="drop"`` scatters.
- every float reduction whose accumulation ORDER matters — the
  fractional segment-sums (requested/passed/processed traffic, the
  release-ring einsums), the admission pipelines' sorted global cumsum
  minus run-start difference, and the masked scalar sums — keeps the
  engine's exact op sequence (same ``jnp.dot``/``einsum``/``cumsum``
  primitives on the same operand arrays), so results are bit-identical,
  not merely close.
- integer reductions (WRR counters, drop counts, ranks, run starts) are
  exact under any order and use scatter-adds.
- the grouping SORT stays ``argsort`` over unique integer keys — exact.

Execution model: ``interpret=None`` auto-selects interpret mode on the
CPU backend exactly like ``pallas_gat`` (tests, 1-core CI, virtual
meshes); there the kernel body inlines into the XLA program as ONE
straight-line block — measurably FEWER fusions than the hand-fused
engine (the fusion-budget test in ``tests/test_megakernel.py`` asserts
pallas < xla on the compiled flagship interval).  On a TPU backend the
call attempts native Mosaic lowering; the ``argsort`` grouping and the
dynamic gathers are not yet expressible there (TPU Pallas has no sort
primitive), so the compiled-TPU port — a bitonic compare-exchange
network over the flow axis, one-hot MXU contractions for the few
order-sensitive segment sums, scalar refs in SMEM — is the documented
next step for a chip window; until then chip runs keep
``substep_impl="xla"``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..sim.engine import (_ARRIVALS_PER_SUBSTEP, _EPS, _HI, _group_order,
                          _onehot, _rank_in_cell, _run_starts)
from ..sim.state import (
    DROP_DECISION,
    DROP_LINK_CAP,
    DROP_NODE_CAP,
    DROP_TTL,
    PH_DECIDE,
    PH_FREE,
    PH_HOP,
    PH_PROC,
    FlowTable,
    SimState,
)

# state fields the substep mutates — the exact ``state.replace(...)`` set
# of SimEngine._substep (run_idx and rng are handled by the caller)
_OUT_KEYS = ("t", "flows", "cursor", "node_load", "sf_available",
             "edge_used", "placed", "sf_startup", "sf_last_active",
             "rel_node", "rel_edge", "metrics", "truncated_arrivals")


def _rows(tab: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``tab[idx]`` rows with out-of-range indices giving ZERO rows — the
    gather twin of the engine's un-clipped one-hot dots (an OOR index
    there matches no ``arange`` column, so the dot returns exact zeros)."""
    return jnp.take(tab, idx, axis=0, mode="fill", fill_value=0)


def _substep_body(sdict, topo_arrs, traf, tabs, cap_now, noise, *, tables,
                  cfg, dims, det):
    """One substep, gather-idiom transcription of ``SimEngine._substep``
    (duration-controller branch).  Stage numbering and comments track the
    engine body line by line; see the module docstring for which ops are
    transcribed verbatim vs re-idiomized."""
    M, N, C, S, P, E, H = dims
    dt = cfg.dt
    path_delay, next_hop, adj_edge_id, edge_cap, edge_delay = topo_arrs
    (arr_time, arr_ingress, arr_dr, arr_duration, arr_ttl, arr_sfc,
     arr_egress) = traf
    # service tables as kernel INPUTS (Pallas forbids captured array
    # constants); values identical to tables.* — `tables` itself only
    # contributes the static resource_fns callables
    chain_len_tab, chain_sf_flat, proc_mean_tab, proc_std_tab, \
        startup_tab = tabs
    capacity = arr_time.shape[0]

    F: FlowTable = sdict["flows"]
    m = sdict["metrics"]
    t = sdict["t"]
    g = jnp.round(t / dt).astype(jnp.int32)       # global substep index
    ridx = jnp.mod(g, H)                           # ring-buffer index
    slots = jnp.arange(M)

    def _demanded(load_plus, avail):
        # twin of SimEngine._demanded: per-SF resource functions
        cols = []
        for si, fn in enumerate(tables.resource_fns):
            cols.append(jnp.where(avail[..., si], fn(load_plus[..., si]),
                                  0.0))
        return jnp.stack(cols, axis=-1).sum(axis=-1)

    # --- 1. capacity releases ------------------------------------------
    node_load = jnp.maximum(
        sdict["node_load"] - sdict["rel_node"][ridx].reshape(N, P), 0.0)
    edge_used = jnp.maximum(sdict["edge_used"] - sdict["rel_edge"][ridx],
                            0.0)
    rel_node = sdict["rel_node"].at[ridx].set(0.0)
    rel_edge = sdict["rel_edge"].at[ridx].set(0.0)
    sf_available = sdict["sf_available"] & (sdict["placed"]
                                            | (node_load > _EPS))

    # --- 2. timers ------------------------------------------------------
    running = (F.phase == PH_HOP) | (F.phase == PH_PROC)
    timer = jnp.where(running, F.timer - dt, F.timer)
    proc_done = (F.phase == PH_PROC) & (timer <= _EPS)
    hop_done = (F.phase == PH_HOP) & (timer <= _EPS)

    position = F.position + proc_done.astype(jnp.int32)
    phase = jnp.where(proc_done, PH_DECIDE, F.phase)

    node = jnp.where(hop_done, F.hop_next, F.node)
    arrived = hop_done & (node == F.dest)
    cont = hop_done & ~arrived
    e2e = F.e2e + jnp.where(arrived, F.pend_path, 0.0)
    ttl = F.ttl - jnp.where(arrived, F.pend_path, 0.0)
    n_arr = arrived.sum()
    path_add = jnp.where(arrived, F.pend_path, 0.0).sum()
    m = m.replace(
        sum_path_delay=m.sum_path_delay + path_add,
        num_path_delay=m.num_path_delay + n_arr,
        run_path_delay_sum=m.run_path_delay_sum + path_add,
    )

    # --- 3. arrivals ----------------------------------------------------
    cand = sdict["cursor"] + jnp.arange(_ARRIVALS_PER_SUBSTEP)
    cand_c = jnp.clip(cand, 0, capacity - 1)
    # ONE packed [A]-row gather per dtype family (the engine's per-array
    # reads, batched; values identical)
    w_flt = jnp.stack([arr_time, arr_dr, arr_duration, arr_ttl],
                      axis=-1)[cand_c]                     # [A, 4]
    w_int = jnp.stack([arr_ingress, arr_sfc, arr_egress],
                      axis=-1)[cand_c]                     # [A, 3]
    w_time, w_dr, w_duration, w_ttl = (w_flt[:, 0], w_flt[:, 1],
                                       w_flt[:, 2], w_flt[:, 3])
    w_ingress, w_sfc, w_egress = w_int[:, 0], w_int[:, 1], w_int[:, 2]
    due = (w_time < t + dt - _EPS) & (cand < capacity) \
        & jnp.isfinite(w_time)
    free = phase == PH_FREE
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    n_free = free.sum()
    arr_rank = jnp.cumsum(due.astype(jnp.int32)) - 1
    spawn = due & (arr_rank < n_free)
    # slot_of_rank: VERBATIM engine transpose-scatter dot — a native
    # scatter lowers to a serial while-loop on the CPU backend, undoing
    # the fusion-count win this body exists for
    oh_rank = _onehot(jnp.where(free, free_rank, M), M)
    slot_of_rank = jnp.round(jnp.dot(slots.astype(jnp.float32), oh_rank,
                                     precision=_HI,
                  preferred_element_type=jnp.float32)).astype(jnp.int32)
    tgt = slot_of_rank[jnp.clip(arr_rank, 0, M - 1)]

    arr_idx = jnp.where(spawn, tgt, M)
    a_i32 = jnp.zeros_like(cand)
    int_cur = jnp.stack([phase, node, position, F.sfc, F.egress, F.dest],
                        axis=-1)                           # [M, 6]
    int_new = jnp.stack([a_i32 + PH_DECIDE, w_ingress,
                         a_i32, w_sfc, w_egress, a_i32 - 1],
                        axis=-1)                           # [A, 6]
    int_cur = int_cur.at[arr_idx].set(int_new, mode="drop")
    phase, node, position, sfc, egress, dest = (
        int_cur[:, 0], int_cur[:, 1], int_cur[:, 2], int_cur[:, 3],
        int_cur[:, 4], int_cur[:, 5])
    a_f32 = jnp.zeros(cand.shape, jnp.float32)
    flt_cur = jnp.stack([F.dr, F.duration, ttl, e2e, F.pend_path],
                        axis=-1)                           # [M, 5]
    flt_new = jnp.stack([w_dr, w_duration, w_ttl, a_f32, a_f32],
                        axis=-1)                           # [A, 5]
    flt_cur = flt_cur.at[arr_idx].set(flt_new, mode="drop")
    dr, duration, ttl, e2e, pend_path = (
        flt_cur[:, 0], flt_cur[:, 1], flt_cur[:, 2], flt_cur[:, 3],
        flt_cur[:, 4])
    hop_next = F.hop_next
    n_spawn = spawn.sum()
    cursor = sdict["cursor"] + n_spawn
    late = spawn & (w_time < t - _EPS)
    truncated = sdict["truncated_arrivals"] + late.sum()
    m = m.replace(
        generated=m.generated + n_spawn,
        run_generated=m.run_generated + n_spawn,
        active=m.active + n_spawn,
        run_requested_node=m.run_requested_node.at[
            jnp.where(spawn, w_ingress, N)
        ].add(jnp.where(spawn, w_dr, 0.0), mode="drop"),
    )

    # recompute flags after arrivals (OOR sfc -> zero chain_len row, the
    # engine's un-clipped one-hot semantics, via mode="fill")
    sfc_c = jnp.clip(sfc, 0, C - 1)
    chain_len = _rows(chain_len_tab, sfc)
    to_eg_flag = position >= chain_len

    # --- 4. decisions ---------------------------------------------------
    deciding = phase == PH_DECIDE
    drop_ttl0 = deciding & (ttl <= _EPS)
    decide = deciding & ~drop_ttl0
    to_eg = decide & to_eg_flag
    egress = jnp.where(to_eg & (egress < 0), node, egress)
    wrr = decide & ~to_eg_flag

    sf_pos = jnp.clip(position, 0, S - 1)
    sf_now = chain_sf_flat[sfc_c * S + sf_pos]   # index always in range
    sf_now = jnp.clip(sf_now, 0)
    oh_node = _onehot(node, N)                 # [M, N]  (segment-sum dots)
    oh_sf = _onehot(sf_now, P)                 # [M, P]
    cell = (node * C + sfc_c) * S + sf_pos
    ncs = N * C * S
    oh_cell = _onehot(cell, ncs)               # [M, NCS] (requested dot)
    placed = sdict["placed"]
    sf_startup = sdict["sf_startup"]
    sf_last_active = sdict["sf_last_active"]
    # requested-traffic metric: fractional segment-sum — VERBATIM dot
    req_add = jnp.dot(jnp.where(wrr, dr, 0.0), oh_cell,
                      precision=_HI,
                  preferred_element_type=jnp.float32).reshape(m.run_requested.shape)
    m = m.replace(run_requested=m.run_requested + req_add)

    # WRR with realized-ratio counters: rank + counter updates VERBATIM
    # (engine helpers / einsum — the scatter forms while-loop on CPU)
    rank = _rank_in_cell(cell, wrr, ncs)
    flow_counts = m.run_flow_counts
    # _rows, not plain indexing: an OOR cell (corrupt node id) must read
    # ZERO rows exactly like the engine's un-clipped oh_cell dots
    probs = _rows(sdict["schedule"].reshape(ncs, N), cell)
    R = cfg.wrr_rank_levels
    for r in range(R):
        sel = wrr & ((rank == r) if r < R - 1 else (rank >= r))
        counts = _rows(flow_counts.reshape(ncs, N), cell)
        total = counts.sum(-1, keepdims=True)
        ratios = jnp.where(total > 0, counts / jnp.maximum(total, 1), 0.0)
        diffs = jnp.where(probs > 0, probs - ratios, -1.0)
        choice = jnp.argmax(diffs, axis=-1).astype(jnp.int32)
        dest = jnp.where(sel, choice, dest)
        cnt_add = jnp.einsum(
            "mc,mn->cn", oh_cell * sel[:, None].astype(jnp.float32),
            _onehot(choice, N), precision=_HI,
                  preferred_element_type=jnp.float32)
        flow_counts = flow_counts + jnp.round(cnt_add).astype(
            flow_counts.dtype).reshape(flow_counts.shape)
    m = m.replace(run_flow_counts=flow_counts)
    dest = jnp.where(to_eg, egress, dest)

    # --- 5. forwarding --------------------------------------------------
    fwd = decide
    stay = fwd & (dest == node)
    depart_stay = to_eg & stay
    need_proc_b = wrr & stay
    start_path = fwd & ~stay
    # the engine's wide [M,N]@[N,3N+1] contraction becomes ONE wide row
    # GATHER; the per-row column picks stay the engine's masked VPU
    # reduces (fusable, and bit-equal by the single-nonzero argument)
    oh_dest = _onehot(jnp.clip(dest, 0), N)
    pd_tab = jnp.where(jnp.isfinite(path_delay), path_delay, 1e30)
    # ALL node-indexed rows in one gather: the engine's loop-invariant
    # [path_delay | next_hop | adj_edge_id | cap_now] block plus its
    # loop-variant [placed | sf_startup] block
    static_tab = jnp.concatenate(
        [pd_tab, next_hop.astype(jnp.float32),
         adj_edge_id.astype(jnp.float32), cap_now[:, None],
         placed.astype(jnp.float32), sf_startup],
        axis=1)                                    # [N, 3N+1+2P]
    rows = _rows(static_tab, node)                 # [M, 3N+1+2P]
    pd_rows = rows[:, :N]
    nh_rows = rows[:, N:2 * N]
    adj_rows = rows[:, 2 * N:3 * N]
    cap_mine = rows[:, 3 * N]
    ps_rows = rows[:, 3 * N + 1:]                  # [M, 2P]
    pd_path = (pd_rows * oh_dest).sum(-1)
    drop_ttl_path = start_path & (ttl - pd_path <= _EPS)
    ttl = jnp.where(drop_ttl_path, 0.0, ttl)
    start_path = start_path & ~drop_ttl_path

    hop_req = cont | start_path
    nh = jnp.round((nh_rows * oh_dest).sum(-1)).astype(jnp.int32)
    nh = jnp.clip(nh, 0)
    eid = jnp.round((adj_rows * _onehot(nh, N)).sum(-1)).astype(jnp.int32)
    eid_c = jnp.clip(eid, 0)
    oh_e = _onehot(eid_c, E)                   # [M, E] (segment-sum dots)
    edge_rows = _rows(jnp.stack(
        [edge_cap - edge_used + _EPS, edge_delay], axis=-1), eid_c)  # [M, 2]
    headroom = edge_rows[:, 0]

    # Hoisted stage-6 pre-sort work (want/pdel before link admission, as
    # in the engine's batched-sort hoist)
    need_proc_a = arrived & ~to_eg_flag
    need_proc = need_proc_a | need_proc_b
    sf_ok = (ps_rows[:, :P] * oh_sf).sum(-1) > 0.5
    drop_unplaced = need_proc & ~sf_ok
    want = need_proc & sf_ok
    proc_tab = _rows(jnp.stack([proc_mean_tab, proc_std_tab, startup_tab],
                               axis=-1), sf_now)   # [M, 3]
    pmean = proc_tab[:, 0]
    pstd = proc_tab[:, 1]
    if det:
        # deterministic processing delays: |N(mean, 0)| == mean (engine's
        # threefry-skip fast path; ``noise`` is unused)
        pdel = jnp.abs(pmean)
    else:
        pdel = jnp.abs(noise * pstd + pmean)
    drop_ttl_pd = want & (ttl - pdel <= _EPS)
    want = want & ~drop_ttl_pd

    # slot-order grouping for link (e) and node (n) admission — the
    # engine's batched argsort + permutation einsum, as two argsorts and
    # ONE packed row gather per pipeline
    orders2 = jax.vmap(_group_order)(jnp.stack([eid_c, node]))   # [2, M]
    order_e, order_n = orders2[0], orders2[1]
    sort_ins = jnp.stack([
        jnp.stack([eid_c.astype(jnp.float32),
                   (hop_req & (eid >= 0)).astype(jnp.float32),
                   dr, headroom], axis=-1),
        jnp.stack([node.astype(jnp.float32), want.astype(jnp.float32),
                   dr, cap_mine], axis=-1)])                     # [2, M, 4]
    sorted2 = jnp.take_along_axis(sort_ins, orders2[:, :, None],
                                  axis=1)          # ONE batched gather
    sorted_e, sorted_n = sorted2[0], sorted2[1]
    eid_s = jnp.round(sorted_e[:, 0]).astype(jnp.int32)
    node_sorted = jnp.round(sorted_n[:, 0]).astype(jnp.int32)
    starts_e = _run_starts(eid_s)
    starts_n = _run_starts(node_sorted)

    req_s = sorted_e[:, 1] > 0.5
    dr_s = sorted_e[:, 2]
    headroom_s = sorted_e[:, 3]
    adm_s = req_s
    for _ in range(cfg.admission_iters):
        # sorted global cumsum minus run-start prefix: VERBATIM float
        # sequence (cs, the run-start row pick, the subtract/compare);
        # only the data movement is gathers
        v = jnp.where(adm_s, dr_s, 0.0)
        cs = jnp.cumsum(v)
        bound = jnp.stack([cs, v], axis=-1)[starts_e]            # [M, 2]
        adm_s = req_s & (cs - (bound[:, 0] - bound[:, 1]) <= headroom_s)
    perm_e = _onehot(order_e, M)
    admitted = jnp.dot(adm_s.astype(jnp.float32), perm_e,
                       precision=_HI,
                  preferred_element_type=jnp.float32) > 0.5        # VERBATIM unsort dot
    drop_link = hop_req & ~admitted
    add_e = jnp.where(admitted, dr, 0.0)
    edge_add = jnp.dot(add_e, oh_e, precision=_HI,
                  preferred_element_type=jnp.float32)   # [E] — VERBATIM dot
    edge_used = edge_used + edge_add
    m = m.replace(run_passed_traffic=m.run_passed_traffic + edge_add)
    hop_delay = edge_rows[:, 1]
    off_e = jnp.clip(jnp.ceil((hop_delay + duration) / dt).astype(jnp.int32),
                     1, H - 1)
    oh_off_e = _onehot(jnp.where(admitted, jnp.mod(ridx + off_e, H), H), H)
    rel_edge = rel_edge + jnp.einsum(
        "mh,me->he", oh_off_e, oh_e * add_e[:, None], precision=_HI,
                  preferred_element_type=jnp.float32)
    pend_path = jnp.where(start_path & admitted, pd_path, pend_path)
    hop_next = jnp.where(admitted, nh, hop_next)
    timer = jnp.where(admitted, hop_delay, timer)
    phase = jnp.where(admitted, PH_HOP, phase)

    # --- 6. processing --------------------------------------------------
    ttl = jnp.where(drop_ttl_pd, 0.0, ttl)
    e2e = e2e + jnp.where(want, pdel, 0.0)
    ttl = ttl - jnp.where(want, pdel, 0.0)
    n_want = want.sum()
    m = m.replace(
        sum_proc_delay=m.sum_proc_delay + jnp.where(want, pdel, 0.0).sum(),
        num_proc_delay=m.num_proc_delay + n_want,
    )
    want_s = sorted_n[:, 1] > 0.5
    dr_col_s = sorted_n[:, 2][:, None]
    cap_s = sorted_n[:, 3]
    la_rows = _rows(jnp.concatenate(
        [node_load, sf_available.astype(jnp.float32)],
        axis=1), node_sorted)                          # [M, 2P]
    base_load_s = la_rows[:, :P]
    avail_s = la_rows[:, P:] > 0.5
    sf_onehot_s = oh_sf[order_n] > 0.5                 # [M, P]
    adm_ns = want_s
    dem_s = jnp.zeros(M, jnp.float32)
    for _ in range(cfg.admission_iters):
        v = jnp.where(adm_ns[:, None] & sf_onehot_s, dr_col_s, 0.0)
        cs = jnp.cumsum(v, axis=0)
        b = jnp.concatenate([cs, v], axis=1)[starts_n]  # [M, 2P]
        dem_s = _demanded(base_load_s + cs - (b[:, :P] - b[:, P:]),
                          avail_s)
        adm_ns = want_s & (dem_s <= cap_s + _EPS)
    perm_n = _onehot(order_n, M)
    unsorted = jnp.dot(
        jnp.stack([adm_ns.astype(jnp.float32), dem_s], axis=-1).T,
        perm_n, precision=_HI,
                  preferred_element_type=jnp.float32)                     # VERBATIM unsort dot
    admitted_n = unsorted[0] > 0.5
    demanded = unsorted[1]
    drop_nodecap = want & ~admitted_n
    add_n = jnp.where(admitted_n, dr, 0.0)
    node_add = jnp.einsum("mn,mp->np", oh_node * add_n[:, None], oh_sf,
                          precision=_HI,
                  preferred_element_type=jnp.float32)               # [N, P] — VERBATIM
    node_load = node_load + node_add
    m = m.replace(
        run_processed_traffic=m.run_processed_traffic + node_add,
        run_max_node_usage=jnp.maximum(
            m.run_max_node_usage,
            (oh_node * jnp.where(admitted_n, demanded, 0.0)[:, None]
             ).max(axis=0)),
    )
    sw = jnp.maximum(
        (ps_rows[:, P:] * oh_sf).sum(-1) + proc_tab[:, 2] - t, 0.0)
    drop_ttl_sw = admitted_n & (ttl - sw <= _EPS) & (sw > _EPS)
    ttl = jnp.where(drop_ttl_sw, 0.0, ttl)
    started = admitted_n & ~drop_ttl_sw
    e2e = e2e + jnp.where(started, sw, 0.0)
    ttl = ttl - jnp.where(started, sw, 0.0)
    busy = jnp.where(started, sw + pdel, 0.0)
    timer = jnp.where(started, busy, timer)
    phase = jnp.where(started, PH_PROC, phase)
    hold = jnp.where(started, busy + duration, dt)
    rel_who = started | drop_ttl_sw
    off_n = jnp.clip(jnp.ceil(hold / dt).astype(jnp.int32), 1, H - 1)
    oh_off_n = _onehot(jnp.where(rel_who, jnp.mod(ridx + off_n, H), H), H)
    rel_vals = jnp.where(rel_who, dr, 0.0)
    np_flat = jnp.einsum("mn,mp->mnp", oh_node * rel_vals[:, None],
                         oh_sf, precision=_HI,
                  preferred_element_type=jnp.float32).reshape(M, N * P)
    rel_node = rel_node + jnp.einsum("mh,mk->hk", oh_off_n, np_flat,
                                     precision=_HI,
                  preferred_element_type=jnp.float32)    # VERBATIM einsums

    # --- 7. departures & drops -----------------------------------------
    depart = (arrived & to_eg_flag) | depart_stay
    n_dep = depart.sum()
    dep_e2e = jnp.where(depart, e2e, 0.0)
    m = m.replace(
        processed=m.processed + n_dep,
        run_processed=m.run_processed + n_dep,
        sum_e2e=m.sum_e2e + dep_e2e.sum(),
        run_e2e_sum=m.run_e2e_sum + dep_e2e.sum(),
        run_e2e_max=jnp.maximum(m.run_e2e_max, dep_e2e.max()),
        active=m.active - n_dep,
    )
    drops = [
        (drop_ttl0, DROP_DECISION),
        (drop_ttl_path, DROP_LINK_CAP),
        (drop_link, DROP_LINK_CAP),
        (drop_unplaced, DROP_NODE_CAP),
        (drop_ttl_pd, DROP_NODE_CAP),
        (drop_nodecap, DROP_NODE_CAP),
        (drop_ttl_sw, DROP_NODE_CAP),
    ]
    any_drop = jnp.zeros(M, bool)
    n_reasons = m.drop_reasons.shape[0]
    adds = [jnp.zeros((), m.drop_reasons.dtype)] * n_reasons
    for mask, reason in drops:
        any_drop = any_drop | mask
        is_ttl = mask & (ttl <= _EPS)
        adds[DROP_TTL] = adds[DROP_TTL] + is_ttl.sum()
        adds[reason] = adds[reason] + (mask & ~is_ttl).sum()
    reasons = m.drop_reasons + jnp.stack(adds)
    n_drop = any_drop.sum()
    m = m.replace(
        drop_reasons=reasons,
        dropped=m.dropped + n_drop,
        run_dropped=m.run_dropped + n_drop,
        active=m.active - n_drop,
        run_dropped_per_node=m.run_dropped_per_node + jnp.round(
            jnp.dot(any_drop.astype(jnp.float32), oh_node,
                    precision=_HI,
                  preferred_element_type=jnp.float32)).astype(m.run_dropped_per_node.dtype),
    )
    gone = depart | any_drop
    phase = jnp.where(gone, PH_FREE, phase)

    # idle-VNF bookkeeping (duration controller: no GC, per-flow control
    # is rejected at SimConfig validation for the pallas impl)
    active_sf = node_load > _EPS
    sf_last_active = jnp.where(active_sf, t, sf_last_active)

    flows = FlowTable(phase=phase, sfc=sfc, position=position, node=node,
                      dest=dest, hop_next=hop_next, egress=egress, dr=dr,
                      duration=duration, ttl=ttl, e2e=e2e,
                      pend_path=pend_path, timer=timer)
    return {
        "t": t + dt, "flows": flows, "cursor": cursor,
        "node_load": node_load, "sf_available": sf_available,
        "edge_used": edge_used, "placed": placed, "sf_startup": sf_startup,
        "sf_last_active": sf_last_active, "rel_node": rel_node,
        "rel_edge": rel_edge, "metrics": m, "truncated_arrivals": truncated,
    }


def _megakernel(*refs, tree_in, scal_in, n_in, tree_out, scal_out, tables,
                cfg, dims, det):
    """Pallas kernel: read every input ref, run the substep body, write
    every output ref.  Scalars travel as (1,) blocks (TPU refs are >=1-d);
    ``scal_*`` records which leaves to re/un-squeeze."""
    vals = [r[...] for r in refs[:n_in]]
    vals = [v[0] if sc else v for v, sc in zip(vals, scal_in)]
    sdict, topo_arrs, traf, tabs, cap_now, noise = \
        jax.tree_util.tree_unflatten(tree_in, vals)
    out = _substep_body(sdict, topo_arrs, traf, tabs, cap_now, noise,
                        tables=tables, cfg=cfg, dims=dims, det=det)
    flat, td = jax.tree_util.tree_flatten(out)
    assert td == tree_out, (td, tree_out)   # trace-time structure check
    for ref, val, sc in zip(refs[n_in:], flat, scal_out):
        ref[...] = val[None] if sc else val


def substep_megakernel(state: SimState, topo, traffic, cap_now: jnp.ndarray,
                       noise: jnp.ndarray, *, tables, cfg, limits, det: bool,
                       interpret: bool | None = None) -> SimState:
    """One simulator substep as a single ``pallas_call``.

    ``state.rng`` must already be advanced by the caller (the engine
    splits and, for stochastic processing delays, draws ``noise`` with
    the SAME key/shape as the XLA path, so the rng STREAM is identical);
    ``run_idx`` is untouched here exactly as in ``SimEngine._substep``.
    ``det`` is the engine's static deterministic-processing-delay flag
    (``noise`` is ignored when set).

    Execution selection:

    - ``interpret=None`` (default): on the CPU backend the kernel BODY is
      inlined as plain XLA — bit-identical to interpret mode (the Pallas
      interpreter executes exactly these jnp ops) but without the
      ref-discharge copies, so the compiled flagship interval lands
      BELOW the hand-fused XLA engine's fusion count (measured 185 vs
      191; the fusion-budget test pins it) and runs ~25% faster per
      interval on CPU.  Other backends take the native ``pallas_call``.
    - ``interpret=True``: force a REAL interpret-mode ``pallas_call``
      (the parity suite uses this to pin kernel == inlined body).
    - ``interpret=False``: force native lowering.
    """
    inline = interpret is None and jax.default_backend() == "cpu"
    if interpret is None:
        interpret = False
    M = cfg.max_flows
    dims = (M, limits.max_nodes, limits.num_sfcs, limits.max_sfs,
            limits.sf_pool, limits.max_edges, cfg.release_horizon)
    sdict = {k: getattr(state, k) for k in
             ("t", "cursor", "flows", "node_load", "sf_available",
              "sf_startup", "sf_last_active", "placed", "schedule",
              "edge_used", "rel_node", "rel_edge", "metrics",
              "truncated_arrivals")}
    topo_arrs = (topo.path_delay, topo.next_hop, topo.adj_edge_id,
                 topo.edge_cap, topo.edge_delay)
    traf = (traffic.arr_time, traffic.arr_ingress, traffic.arr_dr,
            traffic.arr_duration, traffic.arr_ttl, traffic.arr_sfc,
            traffic.arr_egress)
    tabs = (jnp.asarray(tables.chain_len),
            jnp.asarray(tables.chain_sf).reshape(-1),
            jnp.asarray(tables.proc_mean), jnp.asarray(tables.proc_std),
            jnp.asarray(tables.startup_delay))
    if inline:
        out = _substep_body(sdict, topo_arrs, traf, tabs, cap_now, noise,
                            tables=tables, cfg=cfg, dims=dims, det=det)
        return state.replace(**out)
    ins = (sdict, topo_arrs, traf, tabs, cap_now, noise)
    flat_in, tree_in = jax.tree_util.tree_flatten(ins)
    scal_in = tuple(x.ndim == 0 for x in flat_in)
    out_struct = {k: sdict[k] for k in _OUT_KEYS}
    flat_out, tree_out = jax.tree_util.tree_flatten(out_struct)
    scal_out = tuple(x.ndim == 0 for x in flat_out)
    out_shape = tuple(
        jax.ShapeDtypeStruct((1,) if sc else x.shape, x.dtype)
        for x, sc in zip(flat_out, scal_out))
    # every output is an in-place update of the matching state input:
    # alias them (in-VMEM updates on TPU; on CPU it kills the interpret
    # discharge's defensive copies).  The map is built STRUCTURALLY from
    # the dict flatten order (sorted keys; sdict leads the `ins` tuple),
    # never by tracer identity — init-time states can share leaf objects.
    offs, off = {}, 0
    for key in sorted(sdict):
        n_leaves = len(jax.tree_util.tree_leaves(sdict[key]))
        offs[key] = off
        off += n_leaves
    aliases, out_off = {}, 0
    for key in sorted(out_struct):
        for k in range(len(jax.tree_util.tree_leaves(out_struct[key]))):
            aliases[offs[key] + k] = out_off
            out_off += 1
    kern = functools.partial(
        _megakernel, tree_in=tree_in, scal_in=scal_in, n_in=len(flat_in),
        tree_out=tree_out, scal_out=scal_out, tables=tables, cfg=cfg,
        dims=dims, det=det)
    outs = pl.pallas_call(kern, out_shape=out_shape, interpret=interpret,
                          input_output_aliases=aliases)(
        *[x[None] if sc else x for x, sc in zip(flat_in, scal_in)])
    new = jax.tree_util.tree_unflatten(
        tree_out, [o[0] if sc else o for o, sc in zip(outs, scal_out)])
    return state.replace(**new)
