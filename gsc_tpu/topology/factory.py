"""On-device scenario factory — jitted topology/traffic/fault sampling.

PR 9's :class:`~gsc_tpu.topology.scenarios.ScenarioRegistry` generates
every episode's scenario on the host: topology parse, shortest paths,
traffic trace and fault plan are rebuilt in Python per episode per
replica, serialized against the dispatch loop.  Jumanji (PAPERS.md,
arXiv 2306.09884) puts the generator *inside* the compiled program; this
module does the same for the whole scenario: each episode of
``chunk_step`` draws a fresh randomized (topology, traffic, fault plan)
per replica entirely on device — zero host regen, zero retraces across
the stream (every sampled scenario lives in the same fixed
``[max_nodes, max_edges]`` shape bucket, so the dispatch jit sees
identical shapes forever), and an effectively unbounded scenario
distribution instead of a fixed mix string.

Mix grammar (the ``factory:`` extension of the PR 9 mix string,
``EpisodeDriver(topo_mix=...)`` / ``cli train --topo-mix`` /
``bench.py --topo-mix``)::

    factory  := "factory:" families ["+shapes"] ["~faults"]
    families := "all" | family ("-" family)*
    family   := "star" | "ring" | "line" | "random"

A factory mix fills the WHOLE replica axis (it cannot be combined with
registry entries — the registry's round-robin assignment is static,
the factory's is sampled per episode).  ``+shapes`` additionally samples
a traffic shape per replica per episode (uniform / bursty / diurnal /
flash-crowd arrival-mean profiles, the on-device twin of the registry's
``+<shape>`` suffix); ``~faults`` samples a capacity fault plan per
replica per episode (one link- or node-capacity zeroing event from a
random control interval on, riding the same per-interval
``node_cap`` / ``edge_cap_t`` tables the host fault plans use).

What is sampled where (one :meth:`ScenarioFactory.sample` call,
per replica):

- **family** ~ the curriculum's sampling weights (``probs``, a traced
  ``[K]`` vector — uniform without a curriculum), stamped as
  ``topo_id`` so replay rows / the learn ledger attribute per family;
- **topology**: node count within the bucket, integer node caps,
  family-shaped edge list (random family: uniform spanning tree +
  deduplicated extra chords, uniform integer delays), then all-pairs
  shortest paths via an on-device Floyd–Warshall over the reference's
  edge weight ``1/(cap + 1/delay)`` (compiler.py) with path-delay and
  next-hop accumulation — the [N,N] matrices the simulator consumes;
- **traffic**: the shared renewal merge scan
  (:func:`~gsc_tpu.sim.traffic_device.renewal_stream`) over interval
  tables derived from the *sampled* topology and shape row;
- **faults**: Bernoulli(fault_rate) per replica; site (link/node),
  start interval and element index uniform over the topology's REAL
  elements.

Curriculum: :mod:`gsc_tpu.env.curriculum` turns the learn ledger's
per-``topo_idx`` |TD| segment sums into EWMA-driven sampling logits; the
factory just consumes the resulting ``probs`` vector — a fresh tiny
``[K]`` array per episode is data, never a compile axis.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Tuple

import numpy as np

from .compiler import INF_DELAY, Topology

FACTORY_PREFIX = "factory:"
FAMILIES = ("star", "ring", "line", "random")

# traffic-shape profile ids (shape 0 = the plain uniform profile, so a
# shapes-on factory still samples un-shaped episodes)
SHAPE_NAMES = ("uniform", "bursty", "diurnal", "flash_crowd")


@dataclass(frozen=True)
class FactorySpec:
    """Parsed ``factory:`` mix entry + the sampler's static knobs.

    Only the grammar-visible fields come from the mix string; the rest
    are programmatic defaults (construct a spec directly to change
    them).  Frozen/hashable so it can key caches and ride static
    arguments."""

    families: Tuple[str, ...] = FAMILIES
    traffic_shapes: bool = False
    faults: bool = False
    # topology knobs
    n_min: int = 4
    n_max: int = 0                    # 0 = the bucket's max_nodes
    num_ingress: int = 1
    node_cap_range: Tuple[int, int] = (1, 4)   # [lo, hi) integers
    link_cap: float = 100.0
    link_delay: float = 1.0           # star/ring/line fixed delay
    delay_range: Tuple[float, float] = (1.0, 10.0)  # random family
    extra_edge_frac: float = 0.25     # random family chords per node
    # fault knobs
    fault_rate: float = 0.5           # P(any fault) per replica episode

    @property
    def num_families(self) -> int:
        return len(self.families)


_FACTORY_RE = re.compile(r"factory:([a-z-]+)((?:\+shapes|~faults)*)$")


def is_factory_mix(mix) -> bool:
    """True when a mix string selects the on-device factory path."""
    return bool(mix) and mix.strip().startswith(FACTORY_PREFIX)


def parse_factory(mix: str) -> FactorySpec:
    """Parse a ``factory:`` mix entry (grammar in the module docstring).

    A factory mix must be the WHOLE mix string: the registry's
    round-robin replica assignment is static while the factory samples
    per episode, so mixing the two would need two dispatch programs."""
    raw = (mix or "").strip()
    if not is_factory_mix(raw):
        raise ValueError(f"not a factory mix: {mix!r} (expected "
                         f"'{FACTORY_PREFIX}<families>[+shapes][~faults]')")
    if "," in raw:
        raise ValueError(
            "a factory mix fills the whole replica axis and cannot be "
            f"combined with registry entries: {mix!r} (drop the comma "
            "entries or use a pure registry mix)")
    m = _FACTORY_RE.fullmatch(raw)
    if not m:
        raise ValueError(
            f"bad factory mix {mix!r}: expected "
            f"'{FACTORY_PREFIX}<fam>[-<fam>...][+shapes][~faults]' with "
            f"families from {', '.join(FAMILIES)} (or 'all')")
    fams_raw, flags = m.group(1), m.group(2)
    if fams_raw == "all":
        families = FAMILIES
    else:
        families = tuple(fams_raw.split("-"))
        unknown = [f for f in families if f not in FAMILIES]
        if unknown:
            raise ValueError(
                f"unknown factory families {unknown} in {mix!r} "
                f"(known: {', '.join(FAMILIES)}, or 'all')")
        if len(set(families)) != len(families):
            raise ValueError(
                f"duplicate factory families in {mix!r}: two copies of "
                "one family would be identical distributions labeled as "
                "distinct curriculum arms")
    return FactorySpec(families=families,
                       traffic_shapes="+shapes" in flags,
                       faults="~faults" in flags)


# --------------------------------------------------------------- profiles
def _shape_profiles(steps: int) -> np.ndarray:
    """[S, steps] arrival-mean scale profiles, row order
    :data:`SHAPE_NAMES`.  Rows 1..3 call the registry's own profile
    functions (scenarios.TRAFFIC_SHAPES) so the on-device shapes can
    never drift from the host ``+<shape>`` suffix semantics."""
    from .scenarios import TRAFFIC_SHAPES

    rows = [np.ones(steps)]
    for name in SHAPE_NAMES[1:]:
        rows.append(TRAFFIC_SHAPES[name][0](steps))
    return np.stack(rows).astype(np.float32)


def _max_shape_factor(spec: FactorySpec) -> float:
    from .scenarios import TRAFFIC_SHAPES

    if not spec.traffic_shapes:
        return 1.0
    return max(f for _, f in TRAFFIC_SHAPES.values())


# ---------------------------------------------------------------- factory
class ScenarioFactory:
    """Jitted per-(replica, episode) scenario sampler over one shape
    bucket.  Build once per run; ``sample_batch(key, probs, B)`` is one
    device call producing a ``[B]``-stacked ``(Topology,
    TrafficSchedule)`` pair the vmapped dispatch consumes in place of
    the host-staged MixPlan products."""

    def __init__(self, spec: FactorySpec, sim_cfg, service,
                 episode_steps: int, max_nodes: int = 24,
                 max_edges: int = 37):
        from ..sim.traffic import traffic_capacity

        if sim_cfg.use_states:
            raise ValueError(
                "the scenario factory samples arrival means from the "
                "base inter_arrival_mean (+ shape profiles); MMPP state "
                "chains (SimConfig.use_states) are host-table-driven — "
                "use a registry --topo-mix for MMPP scenarios")
        if not spec.families:
            raise ValueError("factory spec has no families")
        # worst-case edge demand per family at node count n: ring needs
        # n, random (n-1) tree edges + extra chords — the bucket must
        # hold the densest possible draw
        def edges_needed(n):
            need = n if "ring" in spec.families else n - 1
            if "random" in spec.families:
                need = max(need, n - 1
                           + int(math.ceil(spec.extra_edge_frac * n)))
            return need

        n_max = spec.n_max
        if not n_max:
            # default: the largest node count whose densest family fits
            # this bucket (so one grammar string works on the 24/37
            # flagship bucket AND the 8/8 test buckets alike)
            n_max = max_nodes
            while n_max > spec.n_min and edges_needed(n_max) > max_edges:
                n_max -= 1
        if not 3 <= spec.n_min <= n_max <= max_nodes:
            raise ValueError(
                f"factory node range [{spec.n_min}, {n_max}] must satisfy "
                f"3 <= n_min <= n_max <= bucket max_nodes ({max_nodes})")
        if edges_needed(n_max) > max_edges:
            raise ValueError(
                f"factory families need up to {edges_needed(n_max)} edges "
                f"at n_max={n_max}, bucket has max_edges={max_edges} — "
                "shrink n_max or widen the bucket")
        self.spec = spec
        self.cfg = sim_cfg
        self.episode_steps = int(episode_steps)
        self.max_nodes = int(max_nodes)
        self.max_edges = int(max_edges)
        self.n_max = int(n_max)
        self.n_sfcs = max(len(service.sfc_names), 1)
        self.horizon = float(episode_steps * sim_cfg.run_duration)
        # one shared traffic capacity across every sampled scenario (the
        # plan_mix convention: densest shape profile, re-rounded to 64)
        cap = traffic_capacity(sim_cfg, spec.num_ingress, episode_steps)
        self.capacity = int(math.ceil(
            cap * _max_shape_factor(spec) / 64.0)) * 64
        # device-resident constants (closed over by the jitted sampler)
        import jax.numpy as jnp
        self.ttl_choices = jnp.asarray(sim_cfg.ttl_choices, jnp.float32)
        self.profiles = jnp.asarray(_shape_profiles(episode_steps))
        self.num_shapes = (len(SHAPE_NAMES) if spec.traffic_shapes else 1)
        self._jit = {}   # B -> jitted sample_batch

    @property
    def family_names(self):
        """topo_id -> family name (the curriculum / learn-ledger segment
        axis)."""
        return list(self.spec.families)

    # ------------------------------------------------------- topology half
    def _random_edges(self, key, n):
        """Random-family edge tensors: a uniform random spanning tree
        (node i's parent uniform over [0, i) — guaranteed connected)
        plus up to ``extra_edge_frac * n`` deduplicated random chords,
        compacted behind the tree edges so ``edge_mask == arange <
        n_edges`` holds like every compiled topology."""
        import jax
        import jax.numpy as jnp

        N, E = self.max_nodes, self.max_edges
        k_par, k_extra, k_delay = jax.random.split(key, 3)
        i = jnp.arange(E)
        # tree slot i connects node i+1 to a uniform parent in [0, i+1)
        parent = jnp.floor(
            jax.random.uniform(k_par, (E,)) * (i + 1)).astype(jnp.int32)
        parent = jnp.minimum(parent, i)   # guard the u==1.0 edge case
        tree_mask = i < n - 1
        tu = jnp.where(tree_mask, parent, N)
        tv = jnp.where(tree_mask, i + 1, N)
        # adjacency over an [N+1] padded grid so masked slots scatter
        # into a discard row; diag blocked so chords never self-loop
        adj = jnp.zeros((N + 1, N + 1), bool)
        adj = adj.at[tu, tv].set(True).at[tv, tu].set(True)
        adj = adj | jnp.eye(N + 1, dtype=bool)
        wanted = jnp.minimum(
            jnp.round(self.spec.extra_edge_frac * n).astype(jnp.int32),
            jnp.int32(E) - (n - 1))

        def chord(carry, c):
            adj, cnt = carry
            ka, kb = jax.random.split(jax.random.fold_in(k_extra, c))
            a = jax.random.randint(ka, (), 0, n)
            b = jax.random.randint(kb, (), 0, n)
            ok = (~adj[a, b]) & (cnt < wanted)
            adj = adj.at[a, b].set(adj[a, b] | ok)
            adj = adj.at[b, a].set(adj[b, a] | ok)
            slot = jnp.where(ok, n - 1 + cnt, E)   # E = discard
            return (adj, cnt + ok.astype(jnp.int32)), (a, b, slot)

        (_, n_extra), (ca, cb, cslot) = jax.lax.scan(
            chord, (adj, jnp.int32(0)), jnp.arange(E))
        eu = jnp.where(tree_mask, parent, 0).astype(jnp.int32)
        ev = jnp.where(tree_mask, i + 1, 0).astype(jnp.int32)
        eu = eu.at[cslot].set(ca.astype(jnp.int32), mode="drop")
        ev = ev.at[cslot].set(cb.astype(jnp.int32), mode="drop")
        n_edges = n - 1 + n_extra
        delay = jnp.round(jax.random.uniform(
            k_delay, (E,), minval=self.spec.delay_range[0],
            maxval=self.spec.delay_range[1]))
        return eu, ev, n_edges, delay

    def _family_edges(self, key, fam, n):
        """(edge_u, edge_v, n_edges, edge_delay) of the sampled family:
        every family's tensors are built (they are a few index ops; the
        random family's tree+chord scan is the only real work) and the
        ``fam`` index selects — one program, no branches to retrace."""
        import jax.numpy as jnp

        E = self.max_edges
        i = jnp.arange(E)
        fixed_delay = jnp.full((E,), jnp.float32(self.spec.link_delay))
        builders = []
        for name in self.spec.families:
            if name == "line":
                builders.append((i, i + 1, n - 1, fixed_delay))
            elif name == "ring":
                builders.append((i, (i + 1) % jnp.maximum(n, 1), n,
                                 fixed_delay))
            elif name == "star":
                builders.append((jnp.zeros((E,), jnp.int32), i + 1, n - 1,
                                 fixed_delay))
            elif name == "random":
                builders.append(self._random_edges(key, n))
            else:   # pragma: no cover - parse_factory validates
                raise ValueError(f"unknown factory family {name!r}")
        eu = jnp.stack([jnp.broadcast_to(b[0], (E,)).astype(jnp.int32)
                        for b in builders])[fam]
        ev = jnp.stack([jnp.broadcast_to(b[1], (E,)).astype(jnp.int32)
                        for b in builders])[fam]
        ne = jnp.stack([jnp.asarray(b[2], jnp.int32)
                        for b in builders])[fam]
        ed = jnp.stack([b[3] for b in builders])[fam]
        mask = i < ne
        return (jnp.where(mask, eu, 0), jnp.where(mask, ev, 0), ne,
                jnp.where(mask, ed, 0.0), mask)

    def _shortest_paths(self, eu, ev, edge_delay, edge_mask, node_mask):
        """On-device all-pairs shortest paths: Floyd–Warshall over the
        reference's edge weight ``1/(cap + 1/delay)`` (compiler.py
        edge_weight; link caps are uniform here, so weights reduce to a
        delay-monotone constant family) with path-DELAY accumulation
        along the chosen paths and next-hop propagation — the same three
        matrices ``compile_topology`` derives via networkx Johnson.
        Tie-breaks may differ from Johnson's (both are valid shortest
        paths); families with unique shortest paths match exactly."""
        import jax
        import jax.numpy as jnp

        N = self.max_nodes
        w = 1.0 / (self.spec.link_cap + 1.0 / jnp.maximum(edge_delay,
                                                          1e-9))
        uu = jnp.where(edge_mask, eu, N)
        vv = jnp.where(edge_mask, ev, N)
        inf = jnp.float32(jnp.inf)
        wadj = jnp.full((N + 1, N + 1), inf)
        wadj = wadj.at[uu, vv].min(w).at[vv, uu].min(w)
        dadj = jnp.full((N + 1, N + 1), inf)
        dadj = dadj.at[uu, vv].min(edge_delay).at[vv, uu].min(edge_delay)
        wadj, dadj = wadj[:N, :N], dadj[:N, :N]
        eye = jnp.eye(N, dtype=bool)
        ii = jnp.arange(N, dtype=jnp.int32)
        dist = jnp.where(eye, 0.0, wadj)
        delay = jnp.where(eye, 0.0, dadj)
        nxt = jnp.where(jnp.isfinite(wadj),
                        jnp.broadcast_to(ii[None, :], (N, N)), -1)
        nxt = jnp.where(eye, ii[:, None], nxt).astype(jnp.int32)

        def relax(k, carry):
            dist, delay, nxt = carry
            alt = dist[:, k][:, None] + dist[k, :][None, :]
            better = alt < dist
            dist = jnp.where(better, alt, dist)
            delay = jnp.where(
                better, delay[:, k][:, None] + delay[k, :][None, :], delay)
            nxt = jnp.where(better,
                            jnp.broadcast_to(nxt[:, k][:, None], (N, N)),
                            nxt)
            return dist, delay, nxt

        dist, delay, nxt = jax.lax.fori_loop(0, N, relax,
                                             (dist, delay, nxt))
        real = node_mask[:, None] & node_mask[None, :]
        reach = real & jnp.isfinite(dist)
        path_delay = jnp.where(reach, delay, INF_DELAY).astype(jnp.float32)
        next_hop = jnp.where(reach, nxt, -1).astype(jnp.int32)
        diameter = jnp.max(jnp.where(reach, path_delay, 0.0))
        return next_hop, path_delay, diameter

    def _sample_topology(self, key, fam, n) -> Topology:
        import jax
        import jax.numpy as jnp

        N, E = self.max_nodes, self.max_edges
        k_edges, k_caps = jax.random.split(key)
        eu, ev, n_edges, edge_delay, edge_mask = self._family_edges(
            k_edges, fam, n)
        node_mask = jnp.arange(N) < n
        lo, hi = self.spec.node_cap_range
        node_cap = jax.random.randint(
            k_caps, (N,), lo, hi).astype(jnp.float32) * node_mask
        n_ing = jnp.maximum(
            jnp.minimum(jnp.int32(self.spec.num_ingress), n - 1), 1)
        is_ingress = jnp.arange(N) < n_ing
        edge_cap = jnp.where(edge_mask, jnp.float32(self.spec.link_cap),
                             0.0)
        # adjacency ids over the [N+1] padded grid (masked slots discard)
        uu = jnp.where(edge_mask, eu, N)
        vv = jnp.where(edge_mask, ev, N)
        ids = jnp.arange(E, dtype=jnp.int32)
        aei = jnp.full((N + 1, N + 1), -1, jnp.int32)
        aei = aei.at[uu, vv].set(ids).at[vv, uu].set(ids)[:N, :N]
        next_hop, path_delay, diameter = self._shortest_paths(
            eu, ev, edge_delay, edge_mask, node_mask)
        return Topology(
            node_cap=node_cap, node_mask=node_mask,
            is_ingress=is_ingress,
            is_egress=jnp.zeros((N,), bool),
            edge_u=eu, edge_v=ev, edge_cap=edge_cap,
            edge_delay=jnp.where(edge_mask, edge_delay, 0.0),
            edge_mask=edge_mask, adj_edge_id=aei,
            next_hop=next_hop, path_delay=path_delay,
            n_nodes=n.astype(jnp.int32), n_edges=n_edges,
            diameter=diameter,
            # family index = the curriculum/learn-ledger segment axis:
            # replay rows collected on this replica attribute to it
            topo_id=fam.astype(jnp.int32),
        )

    # -------------------------------------------------------- traffic half
    def _sample_traffic(self, key, topo: Topology):
        import jax
        import jax.numpy as jnp

        from ..sim.state import TrafficSchedule
        from ..sim.traffic_device import renewal_stream

        steps, N = self.episode_steps, self.max_nodes
        k_shape, k_fault, k_flows = jax.random.split(key, 3)
        ing = topo.is_ingress & topo.node_mask
        shape = (jax.random.randint(k_shape, (), 0, self.num_shapes)
                 if self.num_shapes > 1 else jnp.int32(0))
        profile = self.profiles[shape]                     # [steps]
        means = jnp.where(
            ing[None, :],
            jnp.float32(self.cfg.inter_arrival_mean) * profile[:, None],
            jnp.inf)
        active = jnp.broadcast_to(ing[None, :], (steps, N))
        # activity is time-invariant here, so the next-active table is
        # the identity on ingress columns (steps = never active)
        next_active = jnp.where(
            ing[None, :], jnp.arange(steps, dtype=jnp.int32)[:, None],
            jnp.int32(steps))
        caps = jnp.broadcast_to(topo.node_cap[None, :], (steps, N))
        edge_cap_t = None
        if self.spec.faults:
            k_occ, k_site, k_k0, k_n, k_e = jax.random.split(k_fault, 5)
            occurs = (jax.random.uniform(k_occ, ())
                      < self.spec.fault_rate)
            is_link = jax.random.bernoulli(k_site)
            k0 = jax.random.randint(k_k0, (), 1, max(steps, 2))
            nidx = jax.random.randint(k_n, (), 0, topo.n_nodes)
            eidx = jax.random.randint(k_e, (), 0,
                                      jnp.maximum(topo.n_edges, 1))
            from_k0 = jnp.arange(steps)[:, None] >= k0
            caps = jnp.where(
                (occurs & ~is_link) & from_k0
                & (jnp.arange(N)[None, :] == nidx), 0.0, caps)
            edge_cap_t = jnp.broadcast_to(
                topo.edge_cap[None, :], (steps, self.max_edges))
            edge_cap_t = jnp.where(
                (occurs & is_link) & from_k0
                & (jnp.arange(self.max_edges)[None, :] == eidx),
                0.0, edge_cap_t)
        times, ingress, drs, durs, ttls, sfcs, egs = renewal_stream(
            self.cfg, means, active, next_active, self.horizon,
            self.capacity, self.n_sfcs, self.ttl_choices,
            jnp.zeros((1,), jnp.int32), 0, k_flows)
        return TrafficSchedule(
            arr_time=times, arr_ingress=ingress, arr_dr=drs,
            arr_duration=durs, arr_ttl=ttls, arr_sfc=sfcs, arr_egress=egs,
            ingress_active=active, node_cap=caps, edge_cap_t=edge_cap_t)

    # ------------------------------------------------------------ sampling
    def sample(self, key, probs):
        """One replica's scenario: ``probs`` is the curriculum's ``[K]``
        family-sampling distribution (traced data — fresh values never
        retrace).  Returns ``(Topology, TrafficSchedule)``."""
        import jax
        import jax.numpy as jnp

        k_fam, k_n, k_topo, k_traffic = jax.random.split(key, 4)
        fam = jax.random.choice(k_fam, self.spec.num_families, p=probs)
        n = jax.random.randint(k_n, (), self.spec.n_min, self.n_max + 1)
        topo = self._sample_topology(k_topo, fam.astype(jnp.int32), n)
        return topo, self._sample_traffic(k_traffic, topo)

    def lowerable(self, num_replicas: int):
        """The jitted batch sampler for ``num_replicas`` (built on first
        use, memoized — ONE trace per B for the whole run).  Exposed so
        the cost ledger can AOT-mine the factory-inclusive program."""
        fn = self._jit.get(num_replicas)
        if fn is None:
            import jax

            def factory_sample(key, probs):
                keys = jax.random.split(key, num_replicas)
                return jax.vmap(lambda k: self.sample(k, probs))(keys)

            fn = jax.jit(factory_sample)
            self._jit[num_replicas] = fn
        return fn

    def sample_batch(self, key, probs, num_replicas: int):
        """[B]-stacked (Topology, TrafficSchedule) for one episode — ONE
        jitted device call, the drop-in replacement for the host-staged
        ``MixPlan`` topology + ``mix_traffic`` products."""
        import jax.numpy as jnp

        probs = jnp.asarray(probs, jnp.float32)
        if probs.shape != (self.spec.num_families,):
            raise ValueError(
                f"probs must be [{self.spec.num_families}] (one weight "
                f"per family {self.spec.families}), got {probs.shape}")
        return self.lowerable(num_replicas)(key, probs)
