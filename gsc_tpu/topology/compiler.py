"""Topology compiler: network descriptions -> padded dense pytrees.

Replaces the reference's networkx-resident network representation
(coordsim/reader/reader.py:163-250) with a fixed-shape ``Topology`` pytree
that lives in TPU HBM.  All host-side graph work (shortest paths, geo delays)
happens once at compile time; the simulator then only does O(1) dense lookups
(next-hop matrix, path-delay matrix) — no pointer chasing in the hot loop.

Reference semantics preserved:
- geo link delay from node lat/long: distance/c * 1000 * 0.77, rounded to int
  ms, default 3 when coordinates are missing (reader.py:163-227).  The
  reference uses geopy's geodesic distance; we use the haversine great-circle
  formula (difference <0.5%, and delays are rounded to integer ms).
- edge weight for path selection = 1/(cap + 1/delay), delay==0 -> 0,
  cap==0 -> inf (reader.py:114-126).
- all-pairs shortest paths via Johnson's algorithm with those weights, path
  delay = sum of per-edge delays along the chosen path (reader.py:136-160).
- capacity overrides force_link_cap / force_node_cap (builders.py:9-26).
- ingress/egress node marking via NodeType (reader.py:241-248).

Fixed env limits (default 24 nodes / 37 edges) come from the reference's
generalization mechanism (src/rlsp/envs/gym_env.py:59-66); masks make the
padding explicit.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

SPEED_OF_LIGHT = 299792458  # m/s (reader.py:168)
PROPAGATION_FACTOR = 0.77   # reader.py:169
DEFAULT_LINK_DELAY = 3.0    # reader.py:212
INF_DELAY = 1e9


@struct.dataclass
class Topology:
    """Padded dense topology. All fields are arrays so topologies can be
    stacked along a leading axis and swapped per-replica without recompiling
    (the TPU-native version of the reference's topology scheduler,
    gym_env.py:103-128)."""

    node_cap: jnp.ndarray      # [N] f32, 0 for padding
    node_mask: jnp.ndarray     # [N] bool
    is_ingress: jnp.ndarray    # [N] bool
    is_egress: jnp.ndarray     # [N] bool
    edge_u: jnp.ndarray        # [E] i32 undirected endpoints (0 for padding)
    edge_v: jnp.ndarray        # [E] i32
    edge_cap: jnp.ndarray      # [E] f32
    edge_delay: jnp.ndarray    # [E] f32
    edge_mask: jnp.ndarray     # [E] bool
    adj_edge_id: jnp.ndarray   # [N,N] i32 undirected edge id or -1
    next_hop: jnp.ndarray      # [N,N] i32 first hop from i toward j (i on diag, -1 unreachable)
    path_delay: jnp.ndarray    # [N,N] f32 shortest-path delay (INF_DELAY unreachable)
    n_nodes: jnp.ndarray       # [] i32
    n_edges: jnp.ndarray       # [] i32
    diameter: jnp.ndarray      # [] f32 max finite path delay (reader.py:129-133)
    # position of this topology in its mix/bucket (0 standalone).  Rides the
    # pytree so a vmapped rollout can stamp each replay transition with the
    # network it was collected on (mixed-topology batches) without threading
    # a separate [B] index through every dispatch signature.
    topo_id: jnp.ndarray       # [] i32

    @property
    def max_nodes(self) -> int:
        return self.node_cap.shape[-1]

    @property
    def max_edges(self) -> int:
        return self.edge_cap.shape[-1]

    def directed_edge_index(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Both-direction edge index [2, 2E] + mask [2E] for GNN message
        passing (the analogue of torch_geometric from_networkx edges,
        simulator_wrapper.py:296-299)."""
        src = jnp.concatenate([self.edge_u, self.edge_v])
        dst = jnp.concatenate([self.edge_v, self.edge_u])
        mask = jnp.concatenate([self.edge_mask, self.edge_mask])
        return jnp.stack([src, dst]), mask


@dataclass
class NetworkSpec:
    """Host-side intermediate network description (before padding)."""

    node_caps: List[float]
    node_types: List[str]                      # "Normal" | "Ingress" | "Egress"
    edges: List[Tuple[int, int, float, float]]  # (u, v, cap, delay)
    node_names: List[str] = field(default_factory=list)
    coords: Optional[List[Tuple[float, float]]] = None  # (lat, long)


def haversine_m(lat1: float, lon1: float, lat2: float, lon2: float) -> float:
    """Great-circle distance in meters."""
    r = 6371008.8
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * r * math.asin(math.sqrt(a))


def geo_delay_ms(lat1, lon1, lat2, lon2) -> float:
    """Link delay from geo coordinates (reader.py:223-225)."""
    distance = haversine_m(lat1, lon1, lat2, lon2)
    return float(int(np.around((distance / SPEED_OF_LIGHT * 1000) * PROPAGATION_FACTOR)))


def edge_weight(cap: float, delay: float) -> float:
    """Path-selection weight (reader.py:114-126)."""
    if cap == 0:
        return math.inf
    if delay == 0:
        return 0.0
    return 1.0 / (cap + 1.0 / delay)


def read_graphml(path: str, node_cap: Optional[float] = None,
                 link_cap: float = 1000.0,
                 force_link_cap: Optional[float] = None,
                 force_node_cap: Optional[Tuple[float, float]] = None,
                 rng: Optional[np.random.Generator] = None) -> NetworkSpec:
    """Parse a GraphML network file (reference: reader.py:163-250).

    Node attrs: NodeCap, NodeType (Ingress/Egress/Normal), label, Latitude,
    Longitude.  Edge attrs: LinkFwdCap, LinkDelay (else geo-derived).
    ``force_node_cap=(lo, hi)`` draws integer caps uniformly per node
    (reader.py:183-184); ``force_link_cap`` overrides all link caps.
    """
    import networkx as nx

    if not path.endswith(".graphml"):
        raise ValueError(f"{path} is not a GraphML file")
    if rng is None:
        rng = np.random.default_rng(0)
    g = nx.read_graphml(path, node_type=int)
    order = {n: i for i, n in enumerate(g.nodes())}

    caps, types, names, coords = [], [], [], []
    for n, d in g.nodes(data=True):
        cap = d.get("NodeCap", node_cap)
        if force_node_cap is not None:
            cap = float(rng.integers(int(force_node_cap[0]), int(force_node_cap[1])))
        if cap is None:
            raise ValueError(f"No NodeCap set for node {n} in {path}")
        caps.append(float(cap))
        types.append(d.get("NodeType", "Normal"))
        names.append(d.get("label", f"pop{n}"))
        lat, lon = d.get("Latitude"), d.get("Longitude")
        coords.append((float(lat), float(lon)) if lat is not None and lon is not None
                      else None)

    edges = []
    for u, v, d in g.edges(data=True):
        cap = d.get("LinkFwdCap", link_cap)
        if force_link_cap is not None:
            cap = force_link_cap
        delay = d.get("LinkDelay")
        if delay is None:
            cu, cv = coords[order[u]], coords[order[v]]
            delay = (geo_delay_ms(*cu, *cv) if cu is not None and cv is not None
                     else DEFAULT_LINK_DELAY)
        edges.append((order[u], order[v], float(cap), float(delay)))

    return NetworkSpec(node_caps=caps, node_types=types, edges=edges,
                       node_names=names,
                       coords=[c if c else (0.0, 0.0) for c in coords])


def _all_pairs(spec: NetworkSpec) -> Tuple[np.ndarray, np.ndarray]:
    """All-pairs shortest paths with the reference's weight function, via
    networkx Johnson (reader.py:136-160).  Returns (next_hop, path_delay)."""
    import networkx as nx

    n = len(spec.node_caps)
    g = nx.Graph()
    g.add_nodes_from(range(n))
    delay_of = {}
    for u, v, cap, delay in spec.edges:
        w = edge_weight(cap, delay)
        if math.isinf(w):
            continue  # cap-0 edges can never be selected
        g.add_edge(u, v, weight=w, delay=delay)
        delay_of[(u, v)] = delay
        delay_of[(v, u)] = delay

    next_hop = np.full((n, n), -1, dtype=np.int32)
    path_delay = np.full((n, n), INF_DELAY, dtype=np.float32)
    paths = dict(nx.johnson(g, weight="weight"))
    for s, targets in paths.items():
        for t, path in targets.items():
            d = sum(delay_of[(path[i], path[i + 1])] for i in range(len(path) - 1))
            path_delay[s, t] = d
            next_hop[s, t] = path[1] if len(path) > 1 else s
    return next_hop, path_delay


def compile_topology(spec: NetworkSpec, max_nodes: int = 24,
                     max_edges: int = 37, topo_id: int = 0) -> Topology:
    """Pad + tensorize a NetworkSpec into a Topology pytree."""
    n = len(spec.node_caps)
    e = len(spec.edges)
    if n > max_nodes:
        raise ValueError(f"{n} nodes > max_nodes={max_nodes}")
    if e > max_edges:
        raise ValueError(f"{e} edges > max_edges={max_edges}")

    node_cap = np.zeros(max_nodes, np.float32)
    node_cap[:n] = spec.node_caps
    node_mask = np.zeros(max_nodes, bool)
    node_mask[:n] = True
    is_ingress = np.zeros(max_nodes, bool)
    is_egress = np.zeros(max_nodes, bool)
    for i, t in enumerate(spec.node_types):
        is_ingress[i] = t == "Ingress"
        is_egress[i] = t == "Egress"

    edge_u = np.zeros(max_edges, np.int32)
    edge_v = np.zeros(max_edges, np.int32)
    edge_cap = np.zeros(max_edges, np.float32)
    edge_delay = np.zeros(max_edges, np.float32)
    edge_mask = np.zeros(max_edges, bool)
    adj_edge_id = np.full((max_nodes, max_nodes), -1, np.int32)
    for i, (u, v, cap, delay) in enumerate(spec.edges):
        edge_u[i], edge_v[i] = u, v
        edge_cap[i], edge_delay[i] = cap, delay
        edge_mask[i] = True
        adj_edge_id[u, v] = i
        adj_edge_id[v, u] = i  # undirected: capacity shared both ways (reader.py:229-232)

    nh, pd = _all_pairs(spec)
    next_hop = np.full((max_nodes, max_nodes), -1, np.int32)
    path_delay = np.full((max_nodes, max_nodes), INF_DELAY, np.float32)
    next_hop[:n, :n] = nh
    path_delay[:n, :n] = pd
    finite = pd[pd < INF_DELAY]
    diameter = float(finite.max()) if finite.size else 0.0

    return Topology(
        node_cap=jnp.asarray(node_cap), node_mask=jnp.asarray(node_mask),
        is_ingress=jnp.asarray(is_ingress), is_egress=jnp.asarray(is_egress),
        edge_u=jnp.asarray(edge_u), edge_v=jnp.asarray(edge_v),
        edge_cap=jnp.asarray(edge_cap), edge_delay=jnp.asarray(edge_delay),
        edge_mask=jnp.asarray(edge_mask), adj_edge_id=jnp.asarray(adj_edge_id),
        next_hop=jnp.asarray(next_hop), path_delay=jnp.asarray(path_delay),
        n_nodes=jnp.asarray(n, jnp.int32), n_edges=jnp.asarray(e, jnp.int32),
        diameter=jnp.asarray(diameter, jnp.float32),
        topo_id=jnp.asarray(topo_id, jnp.int32),
    )


def check_dt_quantization(topo: Topology, dt: float,
                          name: str = "") -> bool:
    """Warn when edge delays are not integer multiples of ``dt``.

    The fixed-step engine quantizes hop timers to the substep grid, so a
    link delay of e.g. 5.77 ms at dt=1 releases capacity up to dt early
    relative to the reference's event-driven timeline — measurably different
    contention physics on geo-delay topologies (BT-Europe cap-1: 398 vs 349
    processed at dt=1; exact at dt=0.25 — tests/test_reference_parity.py).
    Returns True when a warning fired so callers/tests can assert on it.
    """
    import warnings

    delays = np.asarray(topo.edge_delay, np.float64)[np.asarray(topo.edge_mask)]

    def _fractional(f):
        # relative tolerance: float32-sourced delays carry ~1e-7 relative
        # representation error, which an absolute cutoff misreads as
        # fractional once f is large (e.g. 4.7/0.1 = 46.999998)
        return np.abs(f - np.round(f)) > 1e-6 * np.maximum(np.abs(f), 1.0)

    bad = _fractional(delays / dt)
    if bad.any():
        suggest = dt
        for cand in (0.5, 0.25, 0.125, 0.1, 0.05, 0.025):
            if not _fractional(delays / cand).any():
                suggest = cand
                break
        label = f" {name!r}" if name else ""
        warnings.warn(
            f"topology{label} has {int(bad.sum())} edge delay(s) that are "
            f"not integer multiples of dt={dt} (e.g. {delays[bad][0]:.3f} ms)"
            f"; the fixed-step engine quantizes hop timers to dt, which "
            f"diverges from the reference's event-driven contention physics"
            + (f" — consider dt={suggest}" if suggest != dt else "")
            + " (see tests/test_reference_parity.py BT-Europe note)",
            stacklevel=2)
        return True
    return False


def load_topology(path: str, max_nodes: int = 24, max_edges: int = 37,
                  force_link_cap: Optional[float] = None,
                  force_node_cap: Optional[Tuple[float, float]] = None,
                  seed: int = 0) -> Topology:
    """GraphML file -> Topology (reference pipeline: builders.py:9-26)."""
    spec = read_graphml(path, force_link_cap=force_link_cap,
                        force_node_cap=force_node_cap,
                        rng=np.random.default_rng(seed))
    return compile_topology(spec, max_nodes=max_nodes, max_edges=max_edges)


def stack_topologies(topos: Sequence[Topology]) -> Topology:
    """Stack topologies along a leading axis for per-replica topology
    diversity (beyond the reference's serial swapping, gym_env.py:103-128)."""
    import jax

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *topos)


# Compiled-topology memo shared by every EpisodeDriver in the process: with
# --runs N (and every schedule re-build) the same GraphML files were parsed
# and shortest-pathed once per driver construction; the key covers every
# input that shapes the compiled pytree, plus the file's mtime so an edited
# asset is never served stale.  Bounded so a long-lived process sweeping
# many files cannot grow it without limit.
_LOAD_MEMO: "OrderedDict" = None
_LOAD_MEMO_MAX = 64


def load_topology_cached(path: str, max_nodes: int = 24, max_edges: int = 37,
                         force_link_cap: Optional[float] = None,
                         force_node_cap: Optional[Tuple[float, float]] = None,
                         seed: int = 0, topo_id: int = 0) -> Topology:
    """Memoized :func:`load_topology` keyed by
    (abspath, mtime, max_nodes, max_edges, force_link_cap, force_node_cap,
    seed, topo_id).  The ``topo_id`` stamp is part of the key and applied
    BEFORE memoization, so the memo returns the SAME Topology object for a
    repeated key — id()-keyed downstream caches (device placement memos,
    per-topology traffic samplers) hit across driver rebuilds for every
    schedule position, not just position 0."""
    global _LOAD_MEMO
    import os
    from collections import OrderedDict

    if _LOAD_MEMO is None:
        _LOAD_MEMO = OrderedDict()
    ap = os.path.abspath(path)
    try:
        mtime = os.path.getmtime(ap)
    except OSError:
        mtime = None   # let load_topology raise its own error
    key = (ap, mtime, max_nodes, max_edges, force_link_cap,
           force_node_cap, seed, topo_id)
    hit = _LOAD_MEMO.get(key)
    if hit is not None:
        _LOAD_MEMO.move_to_end(key)
        return hit
    topo = load_topology(path, max_nodes=max_nodes, max_edges=max_edges,
                         force_link_cap=force_link_cap,
                         force_node_cap=force_node_cap, seed=seed)
    if topo_id:
        topo = topo.replace(topo_id=jnp.asarray(topo_id, jnp.int32))
    _LOAD_MEMO[key] = topo
    while len(_LOAD_MEMO) > _LOAD_MEMO_MAX:
        _LOAD_MEMO.popitem(last=False)
    return topo


class TopologyBucket:
    """Shape bucket: compile K network specs to ONE shared
    (max_nodes, max_edges) padding and stack the compiled pytrees along a
    leading axis, so a single vmapped episode runs them side by side.

    Both layers memoize:

    - ``compile(key, spec, topo_id)`` caches the padded pytree per
      (key, topo_id) — an episode loop that rebuilds its mix every episode
      never re-pads or re-runs shortest paths;
    - ``stack(topos)`` caches the stacked tree per tuple of member object
      ids (the memo retains the member refs, so the ids stay pinned) —
      the stacked tree handed to the vmapped dispatch is the SAME object
      every episode, which is what keeps id()-keyed device-placement
      memos warm and the dispatch retrace-free.
    """

    def __init__(self, max_nodes: int = 24, max_edges: int = 37):
        self.max_nodes = max_nodes
        self.max_edges = max_edges
        self._compiled = {}   # (key, topo_id) -> Topology
        self._stacked = {}    # tuple(id(t)) -> (members, stacked)

    def compile(self, key, spec: NetworkSpec, topo_id: int = 0) -> Topology:
        """Compile ``spec`` into this bucket's padding (memoized per
        (key, topo_id)); raises ValueError when the spec exceeds the
        bucket, naming the bucket dims."""
        memo_key = (key, topo_id)
        hit = self._compiled.get(memo_key)
        if hit is not None:
            return hit
        try:
            topo = compile_topology(spec, max_nodes=self.max_nodes,
                                    max_edges=self.max_edges,
                                    topo_id=topo_id)
        except ValueError as e:
            raise ValueError(
                f"topology {key!r} does not fit bucket "
                f"[{self.max_nodes} nodes, {self.max_edges} edges]: {e}")
        self._compiled[memo_key] = topo
        return topo

    def adopt(self, key, topo: Topology, topo_id: int = 0) -> Topology:
        """Register an ALREADY-compiled topology (e.g. a schedule network
        the driver loaded) under this bucket, re-stamped with ``topo_id``.
        Validates the padding matches the bucket — mixing shapes would
        fail deep inside vmap with an opaque stacking error."""
        if (topo.max_nodes, topo.max_edges) != (self.max_nodes,
                                                self.max_edges):
            raise ValueError(
                f"topology {key!r} is padded to [{topo.max_nodes}, "
                f"{topo.max_edges}], bucket is [{self.max_nodes}, "
                f"{self.max_edges}]")
        memo_key = (key, topo_id)
        hit = self._compiled.get(memo_key)
        if hit is not None:
            return hit
        stamped = topo.replace(topo_id=jnp.asarray(topo_id, jnp.int32))
        self._compiled[memo_key] = stamped
        return stamped

    def stack(self, topos: Sequence[Topology]) -> Topology:
        """Memoized :func:`stack_topologies` over bucket members."""
        key = tuple(id(t) for t in topos)
        hit = self._stacked.get(key)
        if hit is not None:
            return hit[1]
        stacked = stack_topologies(list(topos))
        self._stacked[key] = (tuple(topos), stacked)
        return stacked
