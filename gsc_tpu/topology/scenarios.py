"""Scenario registry + mixed-topology batch planning.

The Jumanji-style scenario layer (PAPERS.md, arXiv 2306.09884) over the
shape-bucket compiler: named GENERATORS for synthetic topologies
(``topology.synthetic``), named TRAFFIC SHAPES (bursty / diurnal /
flash-crowd arrival-mean profiles, applied through the existing trace
machinery), and deterministic mid-episode FAULT plans that zero link/node
capacity rows inside the scanned episode — the simulated-network twin of
the trainer-side fault injection (``gsc_tpu.resilience``), with no host
sync: node faults ride the per-interval ``TrafficSchedule.node_cap``
table, link faults the per-interval ``edge_cap_t`` table the engine
row-selects at each interval start.

Mix grammar (``EpisodeDriver(topo_mix=...)``, ``cli train --topo-mix``,
``bench.py --topo-mix``)::

    mix    := entry ("," entry)*
    entry  := "schedule" | name["+" shape]["~" faults][":" seed]
    faults := fault ("&" fault)*
    fault  := ("link" | "node") "@" interval ["." index]

``schedule`` expands to the scheduler's training topologies; every other
entry names a registry generator (static names plus the dynamic families
``random<N>``, ``star<N>``, ``ring<N>``, ``line<N>``).  The B replica axis
is filled round-robin over the expanded entry list, so one vmapped episode
carries the whole mixture — the "schedule switch" is just a different
per-replica topology tensor, and nothing retraces.

Examples::

    schedule,abilene,random12:7
    abilene+bursty,abilene~link@3.2&node@5.0,ring8:11
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .compiler import NetworkSpec, Topology, TopologyBucket
from . import synthetic


# --------------------------------------------------------------- fault plans
@dataclass(frozen=True)
class TopoFault:
    """One deterministic capacity fault: from control interval ``interval``
    on, the capacity of ``site`` (``link`` = undirected edge id, ``node`` =
    node id) ``index`` is zero.  Persistent — a failed element stays
    failed for the episode's remainder, like a trace cap row."""

    site: str       # "link" | "node"
    interval: int   # control interval the zeroing starts at
    index: int      # edge id (link) / node id (node)


def parse_topo_faults(spec: str) -> Tuple[TopoFault, ...]:
    """``site@interval[.index]`` joined by ``&`` (or ``;`` standalone)."""
    faults = []
    for cell in re.split(r"[&;]", spec):
        cell = cell.strip()
        if not cell:
            continue
        m = re.fullmatch(r"(link|node)@(\d+)(?:\.(\d+))?", cell)
        if not m:
            raise ValueError(
                f"bad fault {cell!r}: expected 'link@<interval>[.<index>]' "
                "or 'node@<interval>[.<index>]'")
        faults.append(TopoFault(site=m.group(1), interval=int(m.group(2)),
                                index=int(m.group(3) or 0)))
    if not faults:
        raise ValueError(f"empty fault plan {spec!r}")
    return tuple(faults)


def validate_faults(topo: Topology, faults: Sequence[TopoFault]):
    """Fault indices must name REAL elements of ``topo`` — padding rows
    never carry traffic, so a fault aimed at one would silently never
    fire and a 'resilience' run would bank healthy-run numbers."""
    n_nodes = int(np.asarray(topo.n_nodes))
    n_edges = int(np.asarray(topo.n_edges))
    for f in faults:
        limit = n_nodes if f.site == "node" else n_edges
        if not 0 <= f.index < limit:
            raise ValueError(
                f"{f.site} fault index {f.index} out of range: topology "
                f"has {limit} real {f.site}s (indices into the padded "
                "tables would silently never fire)")


def apply_faults(topo: Topology, caps: np.ndarray, steps: int,
                 faults: Sequence[TopoFault], with_edge_cap: bool = False):
    """Producer-shared fault application (host ``generate_traffic`` and
    ``DeviceTraffic`` both call this, so their semantics cannot diverge):
    validates indices against the topology's REAL element counts, folds
    node faults into the per-interval ``caps`` table, and materializes the
    ``[T, E]`` edge table when a link fault (or ``with_edge_cap``) needs
    it.  Returns ``(caps, edge_cap_t-or-None)``."""
    import jax.numpy as jnp

    if faults:
        validate_faults(topo, faults)
        caps = apply_node_faults(caps, faults)
    edge_cap_t = None
    if with_edge_cap or any(f.site == "link" for f in faults):
        edge_cap_t = jnp.asarray(build_edge_cap_table(
            np.asarray(topo.edge_cap), steps, faults))
    return caps, edge_cap_t


def apply_node_faults(caps: np.ndarray, faults: Sequence[TopoFault]
                      ) -> np.ndarray:
    """Zero node-capacity rows [T, N] from each fault's interval on (the
    same from-k0-onward semantics as trace cap rows)."""
    caps = np.asarray(caps).copy()
    steps = caps.shape[0]
    for f in faults:
        if f.site != "node":
            continue
        if not 0 <= f.index < caps.shape[1]:
            raise ValueError(f"node fault index {f.index} out of range "
                             f"(max_nodes {caps.shape[1]})")
        caps[min(f.interval, steps):, f.index] = 0.0
    return caps


def build_edge_cap_table(edge_cap: np.ndarray, steps: int,
                         faults: Sequence[TopoFault]) -> np.ndarray:
    """[T, E] per-interval edge capacities: the static caps broadcast over
    time, with link-fault rows zeroed from their interval on."""
    base = np.asarray(edge_cap, np.float32)
    table = np.broadcast_to(base, (steps, base.shape[0])).copy()
    for f in faults:
        if f.site != "link":
            continue
        if not 0 <= f.index < base.shape[0]:
            raise ValueError(f"link fault index {f.index} out of range "
                             f"(max_edges {base.shape[0]})")
        table[min(f.interval, steps):, f.index] = 0.0
    return table


# ------------------------------------------------------------ traffic shapes
def _bursty(steps: int) -> np.ndarray:
    """4-interval on/off blocks: calm (2x the base arrival mean) then
    burst (0.5x), repeating."""
    k = np.arange(steps)
    return np.where((k // 4) % 2 == 0, 2.0, 0.5)


def _diurnal(steps: int) -> np.ndarray:
    """One full daily cycle over the episode: arrival mean swings
    [0.5x, 2.5x] sinusoidally (heavy at the episode start/end)."""
    k = np.arange(steps)
    return 1.5 - np.cos(2.0 * np.pi * k / max(steps, 1))


def _flash_crowd(steps: int) -> np.ndarray:
    """Base traffic with one mid-episode spike window (mean / 8 for
    ~1/8 of the episode) — the sudden-hotspot scenario."""
    scale = np.ones(steps)
    w0 = steps // 2
    scale[w0:w0 + max(steps // 8, 1)] = 0.125
    return scale


# name -> (profile fn: steps -> [steps] arrival-mean scale,
#          traffic-capacity factor covering the densest profile)
TRAFFIC_SHAPES: Dict[str, Tuple[Callable[[int], np.ndarray], float]] = {
    "bursty": (_bursty, 1.3),
    "diurnal": (_diurnal, 1.2),
    "flash_crowd": (_flash_crowd, 1.8),
}


def shape_trace(shape: str, cfg, topo: Topology, steps: int):
    """Trace rows realizing a named traffic shape on every ingress of
    ``topo``: one mean-override row per (interval, ingress), which both
    traffic producers (host ``generate_traffic`` and ``DeviceTraffic``)
    already consume.  Overrides win over the MMPP chain, matching trace
    semantics (trace_processor.py:23-54)."""
    from ..sim.traffic import TraceEvents

    profile_fn, _ = TRAFFIC_SHAPES[shape]
    profile = profile_fn(steps)
    base = cfg.inter_arrival_mean
    ing = np.nonzero(np.asarray(topo.is_ingress)
                     & np.asarray(topo.node_mask))[0]
    rows = [(float(k * cfg.run_duration), int(n),
             float(base * profile[k]), None)
            for k in range(steps) for n in ing]
    return TraceEvents(rows)


# ---------------------------------------------------------------- scenarios
@dataclass(frozen=True)
class Scenario:
    """One parsed mix entry: a named topology generator plus optional
    traffic shape and fault plan.  Deterministic: (name) fully determines
    the generated topology pytree (same seed -> same arrays)."""

    name: str                           # canonical entry string
    topo_name: str
    seed: int = 0
    traffic_shape: Optional[str] = None
    faults: Tuple[TopoFault, ...] = ()


# (pattern, builder, seeded): deterministic families reject a ':<seed>'
# suffix — two seeded copies would be IDENTICAL networks that telemetry
# and banked rows label as distinct mixture members
_DYNAMIC = (
    (re.compile(r"random(\d+)"), lambda n, seed: synthetic.random_network(
        n, seed=seed), True),
    (re.compile(r"star(\d+)"), lambda n, seed: synthetic.star(n), False),
    (re.compile(r"ring(\d+)"), lambda n, seed: synthetic.ring(n), False),
    (re.compile(r"line(\d+)"), lambda n, seed: synthetic.line(n), False),
)

# static registry names whose generator ignores the seed entirely
_SEEDLESS = frozenset({"triangle", "two_node", "claranet", "compuserve"})


class ScenarioRegistry:
    """Named topology generators (``fn(seed) -> NetworkSpec``).  The
    default catalog covers the reference's shipped assets plus the
    synthetic families; ``register`` adds project-specific ones."""

    def __init__(self):
        self._gen: Dict[str, Callable[[int], NetworkSpec]] = {
            "abilene": lambda seed: synthetic.abilene(seed=seed),
            "triangle": lambda seed: synthetic.triangle(),
            "two_node": lambda seed: synthetic.two_node(),
            "bteurope": lambda seed: synthetic.bteurope(
                node_cap_range=(1, 3), seed=seed),
            "claranet": lambda seed: synthetic.claranet(),
            "compuserve": lambda seed: synthetic.compuserve(),
            "tinet": lambda seed: synthetic.tinet(seed=seed),
            "chinanet": lambda seed: synthetic.chinanet(seed=seed),
        }

    def register(self, name: str, fn: Callable[[int], NetworkSpec]):
        self._gen[name] = fn

    def names(self) -> List[str]:
        return sorted(self._gen) + ["random<N>", "star<N>", "ring<N>",
                                    "line<N>"]

    def spec(self, topo_name: str, seed: int = 0) -> NetworkSpec:
        """Deterministic generator lookup (static names first, then the
        dynamic ``<family><N>`` patterns).  A non-zero seed on a
        deterministic generator is an ERROR, not a no-op: ``star8:1`` and
        ``star8:2`` would be identical networks that every banked row and
        telemetry stream labels as distinct mixture members."""
        deterministic = (topo_name in _SEEDLESS)
        fn = self._gen.get(topo_name)
        build = None
        if fn is None:
            for pat, b, seeded in _DYNAMIC:
                m = pat.fullmatch(topo_name)
                if m:
                    build, deterministic = b, not seeded
                    break
            else:
                raise ValueError(
                    f"unknown scenario topology {topo_name!r} (known: "
                    f"{', '.join(self.names())})")
        if seed and deterministic:
            raise ValueError(
                f"{topo_name!r} is a deterministic generator — ':{seed}' "
                "has no effect (two seeded copies would be identical "
                "networks labeled as distinct); drop the seed")
        return fn(seed) if fn is not None else build(int(m.group(1)), seed)

    # ------------------------------------------------------------ parsing
    def parse(self, entry: str) -> Scenario:
        """One mix entry (grammar in the module docstring)."""
        raw = entry.strip()
        if not raw:
            raise ValueError("empty mix entry")
        body, seed = raw, 0
        if ":" in body:
            head, tail = body.rsplit(":", 1)
            if not tail.isdigit():
                raise ValueError(
                    f"bad seed in mix entry {raw!r} (expected ':<int>')")
            body, seed = head, int(tail)
        faults: Tuple[TopoFault, ...] = ()
        if "~" in body:
            body, fspec = body.split("~", 1)
            faults = parse_topo_faults(fspec)
        shape = None
        if "+" in body:
            body, shape = body.split("+", 1)
            if shape not in TRAFFIC_SHAPES:
                raise ValueError(
                    f"unknown traffic shape {shape!r} (known: "
                    f"{', '.join(sorted(TRAFFIC_SHAPES))})")
        self.spec(body, seed)   # validate the generator name NOW
        return Scenario(name=raw, topo_name=body, seed=seed,
                        traffic_shape=shape, faults=faults)

    def parse_mix(self, mix: str) -> List[Union[str, Scenario]]:
        """Comma-separated entry list; ``"schedule"`` passes through as a
        literal for the driver to expand."""
        entries: List[Union[str, Scenario]] = []
        for cell in mix.split(","):
            cell = cell.strip()
            if not cell:
                continue
            entries.append("schedule" if cell == "schedule"
                           else self.parse(cell))
        if not entries:
            raise ValueError(f"empty topology mix {mix!r}")
        return entries


DEFAULT_REGISTRY = ScenarioRegistry()


def validate_mix(mix: str, registry: Optional[ScenarioRegistry] = None):
    """Grammar validation for BOTH mix forms — the one entry point cli
    and bench call before any expensive build.  ``factory:`` mixes parse
    through :mod:`~gsc_tpu.topology.factory` (on-device sampled
    scenarios, the whole replica axis); everything else is a registry
    mix through :meth:`ScenarioRegistry.parse_mix`.  Returns the parsed
    ``FactorySpec`` or entry list."""
    from . import factory as _factory

    if _factory.is_factory_mix(mix):
        return _factory.parse_factory(mix)
    return (registry or DEFAULT_REGISTRY).parse_mix(mix)


# ------------------------------------------------------------- mix planning
@dataclass
class MixEntry:
    """One distinct member of a mixed batch: its compiled (bucketed,
    topo_id-stamped) topology plus the scenario that produced it (None
    for adopted schedule networks, which keep the driver's traffic
    config)."""

    name: str
    topo: Topology
    scenario: Optional[Scenario] = None

    @property
    def faults(self) -> Tuple[TopoFault, ...]:
        return self.scenario.faults if self.scenario else ()

    @property
    def traffic_shape(self) -> Optional[str]:
        return self.scenario.traffic_shape if self.scenario else None


def build_mix_entries(mix: str, registry: ScenarioRegistry,
                      bucket: TopologyBucket,
                      schedule_topos: Optional[Sequence[Topology]] = None,
                      schedule_names: Optional[Sequence[str]] = None,
                      dt: Optional[float] = None) -> List[MixEntry]:
    """Parse + compile a mix string into bucketed entries.  Every entry's
    topology is stamped ``topo_id = entry position`` so replay transitions
    and telemetry can attribute per-network.  Fault indices are validated
    against each entry's REAL element counts here — build time, not first
    traffic production.  ``dt``: run the driver's dt-quantization guard on
    registry-generated topologies (geo-delay members like bteurope/tinet
    warn exactly as their schedule-loaded twins would)."""
    from .compiler import check_dt_quantization

    parsed = registry.parse_mix(mix)
    entries: List[MixEntry] = []
    for item in parsed:
        if item == "schedule":
            if not schedule_topos:
                raise ValueError(
                    "mix entry 'schedule' needs scheduler topologies "
                    "(bench has none — name registry scenarios instead)")
            for i, t in enumerate(schedule_topos):
                name = (schedule_names[i] if schedule_names
                        and i < len(schedule_names) else f"schedule{i}")
                entries.append(MixEntry(
                    name=name,
                    topo=bucket.adopt(("schedule", i), t,
                                      topo_id=len(entries))))
        else:
            spec = registry.spec(item.topo_name, item.seed)
            topo = bucket.compile((item.topo_name, item.seed), spec,
                                  topo_id=len(entries))
            if dt is not None:
                check_dt_quantization(topo, dt, name=item.name)
            validate_faults(topo, item.faults)
            entries.append(MixEntry(name=item.name, topo=topo,
                                    scenario=item))
    return entries


@dataclass
class MixPlan:
    """Round-robin assignment of ``B`` replicas over the mix entries,
    plus the memoized stacked topology the vmapped dispatch consumes.
    Built once (the driver memoizes per B); the stacked tree is the SAME
    object every episode, so downstream id()-keyed placement memos stay
    warm and nothing retraces when the 'schedule switches'."""

    entries: List[MixEntry]
    assignment: np.ndarray          # [B] i64 replica -> entry index
    topo: Topology                  # stacked [B, ...]
    names: List[str]                # [B] per-replica entry names
    capacity: int                   # shared traffic capacity (stackable)
    has_link_faults: bool
    counts: List[int] = field(default_factory=list)   # per-entry replicas
    inv: np.ndarray = None          # [B] concat-order -> replica gather idx

    @property
    def num_entries(self) -> int:
        return len(self.entries)


def plan_mix(entries: Sequence[MixEntry], num_replicas: int,
             bucket: TopologyBucket, cfg, episode_steps: int) -> MixPlan:
    from ..sim.traffic import traffic_capacity

    k = len(entries)
    if num_replicas < k:
        raise ValueError(
            f"num_replicas ({num_replicas}) < mix entries ({k}): the "
            "round-robin fill would silently drop mixture members — "
            "raise --replicas or shrink the mix")
    assignment = np.arange(num_replicas) % k
    counts = [int((assignment == e).sum()) for e in range(k)]
    # one shared traffic capacity so per-replica schedules stack: the max
    # over entries of the config's capacity bound, scaled by the densest
    # profile of the entry's traffic shape (a flash crowd at mean/8 emits
    # ~1.8x the base flow count), re-rounded to 64 for TPU layouts
    caps = []
    for e in entries:
        n_ing = int((np.asarray(e.topo.is_ingress)
                     & np.asarray(e.topo.node_mask)).sum())
        c = traffic_capacity(cfg, n_ing, episode_steps)
        f = TRAFFIC_SHAPES[e.traffic_shape][1] if e.traffic_shape else 1.0
        caps.append(int(math.ceil(c * f / 64.0)) * 64)
    # gather index restoring replica order from per-entry concat order:
    # entry e's o-th replica sits at concat position offset[e] + o and is
    # replica e + o*k
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    inv = offsets[assignment] + np.arange(num_replicas) // k
    return MixPlan(
        entries=list(entries), assignment=assignment,
        topo=bucket.stack([entries[a].topo for a in assignment]),
        names=[entries[a].name for a in assignment],
        capacity=max(caps),
        has_link_faults=any(f.site == "link" for e in entries
                            for f in e.faults),
        counts=counts, inv=inv)


# ------------------------------------------------------- traffic production
def entry_trace(entry: MixEntry, cfg, episode_steps: int,
                default_trace=None):
    """The trace an entry's traffic producer should consume: its shape's
    synthesized rows, or the driver's configured trace for plain/schedule
    entries."""
    if entry.traffic_shape:
        return shape_trace(entry.traffic_shape, cfg, entry.topo,
                           episode_steps)
    return default_trace


def mix_traffic_host(plan: MixPlan, cfg, service, episode_steps: int,
                     seed_for: Callable[[int], int], default_trace=None):
    """[B]-stacked host-generated TrafficSchedule for one episode —
    replica ``r`` seeded by ``seed_for(r)`` on its assigned entry."""
    import jax
    import jax.numpy as jnp

    from ..sim.traffic import generate_traffic

    schedules = []
    for r in range(len(plan.assignment)):
        e = plan.entries[int(plan.assignment[r])]
        schedules.append(generate_traffic(
            cfg, service, e.topo, episode_steps, seed_for(r),
            trace=entry_trace(e, cfg, episode_steps, default_trace),
            capacity=plan.capacity, faults=e.faults,
            with_edge_cap=plan.has_link_faults))
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *schedules)


def mix_device_samplers(plan: MixPlan, cfg, service, episode_steps: int,
                        default_trace=None) -> List:
    """One ``DeviceTraffic`` sampler per mix entry (built once per run)."""
    from ..sim.traffic_device import DeviceTraffic

    return [DeviceTraffic(cfg, service, e.topo, episode_steps,
                          trace=entry_trace(e, cfg, episode_steps,
                                            default_trace),
                          capacity=plan.capacity, faults=e.faults,
                          with_edge_cap=plan.has_link_faults)
            for e in plan.entries]


def sample_mix_device(plan: MixPlan, samplers: Sequence, key):
    """[B]-stacked on-device traffic for one episode: each entry's
    sampler draws its replica share, then one gather interleaves the
    concatenated batches back into replica order (row r belongs to entry
    ``r % K``)."""
    import jax
    import jax.numpy as jnp

    parts = [samplers[e].sample_batch(jax.random.fold_in(key, e),
                                      plan.counts[e])
             for e in range(plan.num_entries)]
    inv = jnp.asarray(plan.inv)
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0)[inv], *parts)
