from .compiler import (
    INF_DELAY,
    NetworkSpec,
    Topology,
    TopologyBucket,
    compile_topology,
    edge_weight,
    geo_delay_ms,
    load_topology,
    load_topology_cached,
    read_graphml,
    stack_topologies,
)
from . import synthetic
from . import scenarios
from .scenarios import (
    DEFAULT_REGISTRY,
    MixEntry,
    MixPlan,
    Scenario,
    ScenarioRegistry,
    TopoFault,
    build_mix_entries,
    parse_topo_faults,
    plan_mix,
    validate_mix,
)
from .factory import (
    FactorySpec,
    ScenarioFactory,
    is_factory_mix,
    parse_factory,
)

__all__ = [
    "INF_DELAY", "NetworkSpec", "Topology", "TopologyBucket",
    "compile_topology", "edge_weight", "geo_delay_ms", "load_topology",
    "load_topology_cached", "read_graphml", "stack_topologies",
    "synthetic", "scenarios", "DEFAULT_REGISTRY", "MixEntry", "MixPlan",
    "Scenario", "ScenarioRegistry", "TopoFault", "build_mix_entries",
    "parse_topo_faults", "plan_mix", "validate_mix", "FactorySpec",
    "ScenarioFactory", "is_factory_mix", "parse_factory",
]
