from .compiler import (
    INF_DELAY,
    NetworkSpec,
    Topology,
    compile_topology,
    edge_weight,
    geo_delay_ms,
    load_topology,
    read_graphml,
    stack_topologies,
)
from . import synthetic

__all__ = [
    "INF_DELAY", "NetworkSpec", "Topology", "compile_topology", "edge_weight",
    "geo_delay_ms", "load_topology", "read_graphml", "stack_topologies",
    "synthetic",
]
