"""Programmatic topology builders + mutation tooling.

Provides the scenario matrix the reference ships as GraphML assets
(configs/networks/: abilene, synthetic triangle/line/2node, randomized-cap
variants) and the topology-mutation utility (scripts/gen_networks.py:6-38)
as code instead of checked-in XML.  The Abilene graph here is built from the
public Internet Topology Zoo node list (city coordinates), with the same
scale as the reference's benchmark scenario: 11 nodes / 14 edges / 4 ingress
(abilene-in4-*: New York, Chicago, Washington DC, Seattle as ingress).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .compiler import NetworkSpec, geo_delay_ms

# (label, lat, long) — public Topology Zoo Abilene city list.
_ABILENE_CITIES = [
    ("New York", 40.71427, -74.00597),
    ("Chicago", 41.85003, -87.65005),
    ("Washington DC", 38.89511, -77.03637),
    ("Seattle", 47.60621, -122.33207),
    ("Sunnyvale", 37.36883, -122.03635),
    ("Los Angeles", 34.05223, -118.24368),
    ("Denver", 39.73915, -104.9847),
    ("Kansas City", 39.11417, -94.62746),
    ("Houston", 29.76328, -95.36327),
    ("Atlanta", 33.749, -84.38798),
    ("Indianapolis", 39.76838, -86.15804),
]
_ABILENE_EDGES = [
    (0, 1), (0, 2), (1, 10), (2, 9), (3, 4), (3, 6), (4, 5), (4, 6),
    (5, 8), (6, 7), (7, 8), (7, 10), (8, 9), (9, 10),
]


def abilene(num_ingress: int = 4, link_cap: float = 1000.0,
            node_cap_range: Tuple[int, int] = (1, 3),
            seed: int = 0) -> NetworkSpec:
    """Abilene with the first ``num_ingress`` cities as ingress and random
    integer node caps in [lo, hi) — the shape of the reference's
    abilene-in4-rand-cap1-2 benchmark scenario."""
    rng = np.random.default_rng(seed)
    n = len(_ABILENE_CITIES)
    caps = [float(rng.integers(*node_cap_range)) for _ in range(n)]
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = []
    for u, v in _ABILENE_EDGES:
        _, lat1, lon1 = _ABILENE_CITIES[u]
        _, lat2, lon2 = _ABILENE_CITIES[v]
        edges.append((u, v, link_cap, geo_delay_ms(lat1, lon1, lat2, lon2)))
    return NetworkSpec(node_caps=caps, node_types=types, edges=edges,
                       node_names=[c[0] for c in _ABILENE_CITIES],
                       coords=[(c[1], c[2]) for c in _ABILENE_CITIES])


# (label, lat, long) — public Internet Topology Zoo "BT Europe" node list
# (the reference's 24-node/37-edge ladder-rung-3 scenario,
# configs/networks/BtEurope-in2-cap1.graphml; its 24/37 scale is exactly
# the reference's padding limits, environment_limits.py:44-64).  New York
# and Washington are satellite/transatlantic PoPs without coordinates in
# Topology Zoo; their links use the reader's 3 ms default (reader.py:212).
_BTEUROPE_CITIES = [
    ("Budapest", 47.49801, 19.03991),
    ("Munich", 48.13743, 11.57549),
    ("Prague", 50.08804, 14.42076),
    ("Vienna", 48.20849, 16.37208),
    ("Dusseldorf", 51.22172, 6.77616),
    ("Frankfurt", 50.11667, 8.68333),
    ("Zurich", 47.36667, 8.55),
    ("Paris", 48.85341, 2.3488),
    ("Milan", 45.46427, 9.18951),
    ("Barcelona", 41.38879, 2.15899),
    ("Goonhilly", 50.05, -5.2),
    ("New York", None, None),
    ("Washington", None, None),
    ("Madrid", 40.4165, -3.70256),
    ("Helsinki", 60.16952, 24.93545),
    ("Copenhagen", 55.67594, 12.56553),
    ("London1", 51.50853, -0.12574),
    ("London2", 51.50853, -0.12574),
    ("Madley", 52.03333, -2.85),
    ("Dublin", 53.34399, -6.26719),
    ("Brussels", 50.85045, 4.34878),
    ("Amsterdam", 52.37403, 4.88969),
    ("Gothenburg", 57.70716, 11.96679),
    ("Stockholm", 59.33258, 18.0649),
]
_BTEUROPE_EDGES = [
    (0, 17), (0, 5), (1, 4), (1, 5), (2, 16), (2, 5), (3, 5), (3, 21),
    (4, 5), (4, 21), (5, 6), (5, 8), (5, 17), (5, 21), (6, 17), (7, 17),
    (7, 21), (8, 17), (9, 13), (9, 21), (10, 17), (11, 17), (12, 16),
    (13, 17), (14, 23), (15, 23), (16, 17), (16, 21), (16, 23), (17, 18),
    (17, 19), (17, 20), (17, 21), (19, 21), (21, 22), (21, 23), (22, 23),
]


def bteurope(num_ingress: int = 2, link_cap: float = 1000.0,
             node_cap: float = 1.0,
             node_cap_range: Optional[Tuple[int, int]] = None,
             seed: int = 0) -> NetworkSpec:
    """BT Europe (Topology Zoo): 24 nodes / 37 edges, first ``num_ingress``
    nodes ingress — the BtEurope-in2-cap1 rung-3 scenario shape.  With
    ``node_cap_range`` caps are random integers in [lo, hi) like the
    rand-cap variants."""
    rng = np.random.default_rng(seed)
    n = len(_BTEUROPE_CITIES)
    if node_cap_range is not None:
        caps = [float(rng.integers(*node_cap_range)) for _ in range(n)]
    else:
        caps = [float(node_cap)] * n
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = []
    for u, v in _BTEUROPE_EDGES:
        _, lat1, lon1 = _BTEUROPE_CITIES[u]
        _, lat2, lon2 = _BTEUROPE_CITIES[v]
        if None in (lat1, lon1, lat2, lon2):
            delay = 3.0  # reader.py:212 default when geo data is missing
        else:
            delay = geo_delay_ms(lat1, lon1, lat2, lon2)
        edges.append((u, v, link_cap, delay))
    return NetworkSpec(
        node_caps=caps, node_types=types, edges=edges,
        node_names=[c[0] for c in _BTEUROPE_CITIES],
        coords=[(c[1] or 0.0, c[2] or 0.0) for c in _BTEUROPE_CITIES])


# Internet Topology Zoo graph structures for the reference's two other
# small real scenarios (Claranet-in4, Compuserve-in4).  The reference's
# assets carry no coordinates, so every link uses the reader's 3 ms
# default delay (reader.py:212).
_CLARANET_EDGES = [  # 15 nodes / 18 edges
    (0, 3), (1, 3), (1, 4), (2, 3), (3, 14), (4, 12), (5, 14), (6, 14),
    (7, 8), (7, 10), (7, 14), (9, 10), (9, 11), (10, 11), (10, 12),
    (10, 14), (12, 13), (12, 14),
]
_COMPUSERVE_EDGES = [  # 14 nodes / 17 edges
    (0, 12), (1, 12), (2, 11), (2, 12), (2, 5), (3, 12), (4, 5), (4, 13),
    (6, 13), (6, 7), (7, 8), (7, 12), (8, 9), (9, 10), (9, 12), (10, 11),
    (12, 13),
]


def _zoo_network(n: int, edge_list, num_ingress: int, link_cap: float,
                 node_cap: float, link_delay: float = 3.0) -> NetworkSpec:
    caps = [float(node_cap)] * n
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = [(u, v, link_cap, link_delay) for u, v in edge_list]
    return NetworkSpec(node_caps=caps, node_types=types, edges=edges)


def claranet(num_ingress: int = 4, link_cap: float = 1000.0,
             node_cap: float = 1.0) -> NetworkSpec:
    """Claranet (Topology Zoo): 15 nodes / 18 edges — the reference's
    Claranet-in4-cap1 scenario shape."""
    return _zoo_network(15, _CLARANET_EDGES, num_ingress, link_cap, node_cap)


def compuserve(num_ingress: int = 4, link_cap: float = 1000.0,
               node_cap: float = 1.0) -> NetworkSpec:
    """Compuserve (Topology Zoo): 14 nodes / 17 edges — the reference's
    Compuserve-in4-cap1 scenario shape."""
    return _zoo_network(14, _COMPUSERVE_EDGES, num_ingress, link_cap,
                        node_cap)


def triangle(node_caps: Sequence[float] = (10.0, 10.0, 10.0),
             link_cap: float = 100.0, link_delay: float = 1.0,
             num_ingress: int = 1) -> NetworkSpec:
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(3)]
    edges = [(0, 1, link_cap, link_delay), (1, 2, link_cap, link_delay),
             (0, 2, link_cap, link_delay)]
    return NetworkSpec(node_caps=list(node_caps), node_types=types, edges=edges)


def line(n: int = 3, node_cap: float = 10.0, link_cap: float = 100.0,
         link_delay: float = 1.0, num_ingress: int = 1) -> NetworkSpec:
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = [(i, i + 1, link_cap, link_delay) for i in range(n - 1)]
    return NetworkSpec(node_caps=[node_cap] * n, node_types=types, edges=edges)


def two_node(node_caps: Sequence[float] = (5.0, 5.0), link_cap: float = 100.0,
             link_delay: float = 1.0) -> NetworkSpec:
    return NetworkSpec(node_caps=list(node_caps),
                       node_types=["Ingress", "Normal"],
                       edges=[(0, 1, link_cap, link_delay)])


def random_network(n_nodes: int, avg_degree: float = 2.5,
                   node_cap_range: Tuple[int, int] = (1, 4),
                   link_cap: float = 1000.0,
                   delay_range: Tuple[float, float] = (1.0, 10.0),
                   num_ingress: int = 4, seed: int = 0) -> NetworkSpec:
    """Random connected topology, the programmatic analogue of the
    gen_networks.py-mutated training sets (scripts/gen_networks.py +
    BASELINE config 4: 64-128 node randomized topologies)."""
    rng = np.random.default_rng(seed)
    caps = [float(rng.integers(*node_cap_range)) for _ in range(n_nodes)]
    ing = rng.choice(n_nodes, size=min(num_ingress, n_nodes), replace=False)
    types = ["Ingress" if i in ing else "Normal" for i in range(n_nodes)]
    edges: List[Tuple[int, int, float, float]] = []
    seen = set()

    def add(u, v):
        if u != v and (u, v) not in seen and (v, u) not in seen:
            seen.add((u, v))
            edges.append((u, v, link_cap, float(np.around(rng.uniform(*delay_range)))))

    # random spanning tree first (guarantees connectivity)
    perm = rng.permutation(n_nodes)
    for i in range(1, n_nodes):
        add(int(perm[rng.integers(0, i)]), int(perm[i]))
    target_edges = min(int(avg_degree * n_nodes / 2),
                       n_nodes * (n_nodes - 1) // 2)
    while len(edges) < target_edges:
        add(int(rng.integers(n_nodes)), int(rng.integers(n_nodes)))
    return NetworkSpec(node_caps=caps, node_types=types, edges=edges)


def mutate_caps(spec: NetworkSpec, cap_range: Tuple[int, int],
                seed: int = 0) -> NetworkSpec:
    """Rewrite node caps with random values (gen_networks.py:6-21)."""
    rng = np.random.default_rng(seed)
    return NetworkSpec(
        node_caps=[float(rng.integers(*cap_range)) for _ in spec.node_caps],
        node_types=list(spec.node_types), edges=list(spec.edges),
        node_names=list(spec.node_names),
        coords=list(spec.coords) if spec.coords else None)


def set_ingress(spec: NetworkSpec, nodes: Sequence[int]) -> NetworkSpec:
    """Mark the given nodes as Ingress (gen_networks.py:24-38)."""
    types = ["Ingress" if i in set(nodes) else t
             for i, t in enumerate(spec.node_types)]
    return NetworkSpec(node_caps=list(spec.node_caps), node_types=types,
                       edges=list(spec.edges), node_names=list(spec.node_names),
                       coords=list(spec.coords) if spec.coords else None)


def write_graphml(spec: NetworkSpec, path: str) -> None:
    """Persist a NetworkSpec as a reference-compatible GraphML asset."""
    import networkx as nx

    g = nx.Graph()
    for i, cap in enumerate(spec.node_caps):
        attrs = dict(NodeCap=cap, NodeType=spec.node_types[i])
        if spec.node_names:
            attrs["label"] = spec.node_names[i]
        if spec.coords:
            attrs["Latitude"], attrs["Longitude"] = spec.coords[i]
        g.add_node(i, **attrs)
    for u, v, cap, delay in spec.edges:
        g.add_edge(u, v, LinkFwdCap=cap, LinkDelay=delay)
    nx.write_graphml(g, path)
