"""Programmatic topology builders + mutation tooling.

Provides the scenario matrix the reference ships as GraphML assets
(configs/networks/: abilene, synthetic triangle/line/2node, randomized-cap
variants) and the topology-mutation utility (scripts/gen_networks.py:6-38)
as code instead of checked-in XML.  The Abilene graph here is built from the
public Internet Topology Zoo node list (city coordinates), with the same
scale as the reference's benchmark scenario: 11 nodes / 14 edges / 4 ingress
(abilene-in4-*: New York, Chicago, Washington DC, Seattle as ingress).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .compiler import NetworkSpec, geo_delay_ms

# (label, lat, long) — public Topology Zoo Abilene city list.
_ABILENE_CITIES = [
    ("New York", 40.71427, -74.00597),
    ("Chicago", 41.85003, -87.65005),
    ("Washington DC", 38.89511, -77.03637),
    ("Seattle", 47.60621, -122.33207),
    ("Sunnyvale", 37.36883, -122.03635),
    ("Los Angeles", 34.05223, -118.24368),
    ("Denver", 39.73915, -104.9847),
    ("Kansas City", 39.11417, -94.62746),
    ("Houston", 29.76328, -95.36327),
    ("Atlanta", 33.749, -84.38798),
    ("Indianapolis", 39.76838, -86.15804),
]
_ABILENE_EDGES = [
    (0, 1), (0, 2), (1, 10), (2, 9), (3, 4), (3, 6), (4, 5), (4, 6),
    (5, 8), (6, 7), (7, 8), (7, 10), (8, 9), (9, 10),
]


def abilene(num_ingress: int = 4, link_cap: float = 1000.0,
            node_cap_range: Tuple[int, int] = (1, 3),
            seed: int = 0) -> NetworkSpec:
    """Abilene with the first ``num_ingress`` cities as ingress and random
    integer node caps in [lo, hi) — the shape of the reference's
    abilene-in4-rand-cap1-2 benchmark scenario."""
    return _geo_zoo_network(_ABILENE_CITIES, _ABILENE_EDGES, num_ingress,
                            link_cap, node_cap_range, seed)


# (label, lat, long) — public Internet Topology Zoo "BT Europe" node list
# (the reference's 24-node/37-edge ladder-rung-3 scenario,
# configs/networks/BtEurope-in2-cap1.graphml; its 24/37 scale is exactly
# the reference's padding limits, environment_limits.py:44-64).  New York
# and Washington are satellite/transatlantic PoPs without coordinates in
# Topology Zoo; their links use the reader's 3 ms default (reader.py:212).
_BTEUROPE_CITIES = [
    ("Budapest", 47.49801, 19.03991),
    ("Munich", 48.13743, 11.57549),
    ("Prague", 50.08804, 14.42076),
    ("Vienna", 48.20849, 16.37208),
    ("Dusseldorf", 51.22172, 6.77616),
    ("Frankfurt", 50.11667, 8.68333),
    ("Zurich", 47.36667, 8.55),
    ("Paris", 48.85341, 2.3488),
    ("Milan", 45.46427, 9.18951),
    ("Barcelona", 41.38879, 2.15899),
    ("Goonhilly", 50.05, -5.2),
    ("New York", None, None),
    ("Washington", None, None),
    ("Madrid", 40.4165, -3.70256),
    ("Helsinki", 60.16952, 24.93545),
    ("Copenhagen", 55.67594, 12.56553),
    ("London1", 51.50853, -0.12574),
    ("London2", 51.50853, -0.12574),
    ("Madley", 52.03333, -2.85),
    ("Dublin", 53.34399, -6.26719),
    ("Brussels", 50.85045, 4.34878),
    ("Amsterdam", 52.37403, 4.88969),
    ("Gothenburg", 57.70716, 11.96679),
    ("Stockholm", 59.33258, 18.0649),
]
_BTEUROPE_EDGES = [
    (0, 17), (0, 5), (1, 4), (1, 5), (2, 16), (2, 5), (3, 5), (3, 21),
    (4, 5), (4, 21), (5, 6), (5, 8), (5, 17), (5, 21), (6, 17), (7, 17),
    (7, 21), (8, 17), (9, 13), (9, 21), (10, 17), (11, 17), (12, 16),
    (13, 17), (14, 23), (15, 23), (16, 17), (16, 21), (16, 23), (17, 18),
    (17, 19), (17, 20), (17, 21), (19, 21), (21, 22), (21, 23), (22, 23),
]


def bteurope(num_ingress: int = 2, link_cap: float = 1000.0,
             node_cap: float = 1.0,
             node_cap_range: Optional[Tuple[int, int]] = None,
             seed: int = 0) -> NetworkSpec:
    """BT Europe (Topology Zoo): 24 nodes / 37 edges, first ``num_ingress``
    nodes ingress — the BtEurope-in2-cap1 rung-3 scenario shape.  With
    ``node_cap_range`` caps are random integers in [lo, hi) like the
    rand-cap variants."""
    return _geo_zoo_network(_BTEUROPE_CITIES, _BTEUROPE_EDGES, num_ingress,
                            link_cap, node_cap_range, seed,
                            node_cap=node_cap)


# Internet Topology Zoo graph structures for the reference's two other
# small real scenarios (Claranet-in4, Compuserve-in4).  The reference's
# assets carry no coordinates, so every link uses the reader's 3 ms
# default delay (reader.py:212).
_CLARANET_EDGES = [  # 15 nodes / 18 edges
    (0, 3), (1, 3), (1, 4), (2, 3), (3, 14), (4, 12), (5, 14), (6, 14),
    (7, 8), (7, 10), (7, 14), (9, 10), (9, 11), (10, 11), (10, 12),
    (10, 14), (12, 13), (12, 14),
]
_COMPUSERVE_EDGES = [  # 14 nodes / 17 edges
    (0, 12), (1, 12), (2, 11), (2, 12), (2, 5), (3, 12), (4, 5), (4, 13),
    (6, 13), (6, 7), (7, 8), (7, 12), (8, 9), (9, 10), (9, 12), (10, 11),
    (12, 13),
]


def _zoo_network(n: int, edge_list, num_ingress: int, link_cap: float,
                 node_cap: float, link_delay: float = 3.0) -> NetworkSpec:
    caps = [float(node_cap)] * n
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = [(u, v, link_cap, link_delay) for u, v in edge_list]
    return NetworkSpec(node_caps=caps, node_types=types, edges=edges)


def claranet(num_ingress: int = 4, link_cap: float = 1000.0,
             node_cap: float = 1.0) -> NetworkSpec:
    """Claranet (Topology Zoo): 15 nodes / 18 edges — the reference's
    Claranet-in4-cap1 scenario shape."""
    return _zoo_network(15, _CLARANET_EDGES, num_ingress, link_cap, node_cap)


def compuserve(num_ingress: int = 4, link_cap: float = 1000.0,
               node_cap: float = 1.0) -> NetworkSpec:
    """Compuserve (Topology Zoo): 14 nodes / 17 edges — the reference's
    Compuserve-in4-cap1 scenario shape."""
    return _zoo_network(14, _COMPUSERVE_EDGES, num_ingress, link_cap,
                        node_cap)


# (label, lat, long) — public Internet Topology Zoo "Tinet" (ex-Tiscali)
# node list: 53 nodes / 89 edges, the reference's mid-size real scenario
# (configs/networks/tinet/, in2..in17 rand-cap0-2 variants).  Unnamed /
# unlocated PoPs keep None coordinates → links touching them use the
# reader's 3 ms default delay (reader.py:212).
_TINET_CITIES = [
    ("New York", 40.71427, -74.00597), ("PoP1", None, None),
    ("Montreal", 45.50884, -73.58781), ("Boston", 42.35843, -71.05977),
    ("London", 51.50853, -0.12574), ("Amsterdam", 52.37403, 4.88969),
    ("Dublin", 53.34399, -6.26719), ("Manchester", 53.48095, -2.23743),
    ("Dusseldorf", 51.22172, 6.77616), ("Antwerp", 51.21667, 4.41667),
    ("PoP10", None, None), ("PoP11", None, None), ("PoP12", None, None),
    ("Athens", 37.97945, 23.71622), ("Bucharest", 44.43225, 26.10626),
    ("Vienna", 48.20849, 16.37208), ("Bratislava", 48.14816, 17.10674),
    ("Prague", 50.08804, 14.42076), ("Warsaw", 52.22977, 21.01178),
    ("Cagliari", 39.20738, 9.13462), ("Rome", 41.89474, 12.4839),
    ("Berlin", 52.52437, 13.41053), ("Catania", 37.50213, 15.08719),
    ("Madrid", 40.4165, -3.70256), ("Singapore", 1.28967, 103.85007),
    ("Hamburg", 53.55, 10.0), ("Sofia", 42.69751, 23.32415),
    ("Oslo", 59.91273, 10.74609), ("Copenhagen", 55.67594, 12.56553),
    ("Palo Alto", 37.44188, -122.14302), ("Stockholm", 59.33258, 18.0649),
    ("Hong Kong", 22.28552, 114.15769), ("PoP32", None, None),
    ("Munich", 48.13743, 11.57549), ("Frankfurt", 50.11667, 8.68333),
    ("Marseille", 43.3, 5.4), ("Barcelona", 41.38879, 2.15899),
    ("Paris", 48.85341, 2.3488), ("Brussels", 50.85045, 4.34878),
    ("Basel", 47.56667, 7.6), ("Zurich", 47.36667, 8.55),
    ("Milan", 45.46427, 9.18951), ("Turin", 45.07049, 7.68682),
    ("Miami", 25.77427, -80.19366), ("Toronto", 43.70011, -79.4163),
    ("Seattle", 47.60621, -122.33207), ("San Jose", 37.33939, -121.89496),
    ("Los Angeles", 34.05223, -118.24368), ("Denver", 39.73915, -104.9847),
    ("Chicago", 41.85003, -87.65005), ("Dallas", 32.78306, -96.80667),
    ("Atlanta", 33.749, -84.38798), ("Washington DC", 38.89511, -77.03637),
]
_TINET_EDGES = [
    (0, 1), (0, 6), (1, 10), (1, 44), (1, 49), (1, 52), (2, 3), (2, 6),
    (4, 5), (4, 6), (4, 7), (4, 8), (4, 37), (4, 38), (5, 7), (5, 8),
    (5, 9), (5, 27), (5, 28), (5, 34), (6, 7), (8, 18), (8, 25), (8, 30),
    (8, 34), (9, 38), (10, 11), (10, 12), (11, 46), (11, 48), (12, 48),
    (12, 49), (13, 22), (14, 15), (14, 34), (15, 16), (15, 32), (15, 33),
    (15, 34), (16, 17), (17, 18), (17, 34), (18, 34), (19, 20), (19, 42),
    (20, 21), (20, 22), (21, 42), (22, 41), (23, 36), (23, 37), (24, 31),
    (24, 35), (24, 46), (25, 28), (26, 32), (27, 28), (27, 30), (28, 30),
    (29, 49), (31, 46), (31, 47), (32, 34), (33, 34), (33, 39), (34, 37),
    (34, 39), (34, 41), (35, 36), (35, 37), (35, 41), (35, 42), (37, 38),
    (37, 39), (37, 42), (39, 40), (40, 41), (41, 42), (43, 51), (43, 52),
    (44, 45), (44, 49), (45, 46), (46, 47), (47, 50), (49, 50), (49, 52),
    (50, 51), (51, 52),
]

# (label, lat, long) — public Internet Topology Zoo "Chinanet" node list:
# 42 nodes / 66 edges (configs/networks/chinanet/, in2..in14 variants).
_CHINANET_CITIES = [
    ("Lhasa", 29.65, 91.1), ("Lanzhou", 36.05639, 103.79222),
    ("Kashi", 39.45472, 75.97972), ("Shiquanhe", 32.51667, 80.06667),
    ("Jinan", 36.66833, 116.99722), ("Qingdao", 36.09861, 120.37194),
    ("Taiyuan", 37.86944, 112.56028), ("Shijiazhuang", 38.04139, 114.47861),
    ("Shanghai", 31.22222, 121.45806), ("Suzhou", 31.31139, 120.61806),
    ("IntlLink1", None, None), ("IntlLink2", None, None),
    ("Nanning", 22.81667, 108.31667), ("Changsha", 28.2, 112.96667),
    ("Guiyang", 26.58333, 106.71667), ("Chongqing", 29.56278, 106.55278),
    ("Chengdu", 30.66667, 104.06667), ("Kunming", 25.03889, 102.71833),
    ("Xi'an", 34.25833, 108.92861), ("Zhengzhou", 34.75778, 113.64861),
    ("IntlLink4", None, None), ("IntlLink3", None, None),
    ("Haikou", 20.04583, 110.34167), ("Hong Kong", 30.13062, 100.51803),
    ("Hangzhou", 30.25528, 120.16889), ("Wuhan", 30.58333, 114.26667),
    ("Hefei", 31.86389, 117.28083), ("Nanjing", 32.06167, 118.77778),
    ("Guangzhou", 23.11667, 113.25), ("Xiamen", 24.47979, 118.08187),
    ("Fuzhou", 26.06139, 119.30611), ("Nanchang", 28.68333, 115.88333),
    ("Xining", 36.61667, 101.76667), ("Urumqi", 43.8, 87.58333),
    ("Harbin", 45.75, 126.65), ("Changchun", 43.88, 125.32278),
    ("Shenyang", 41.79222, 123.43278), ("Dalian", 38.91222, 121.60222),
    ("Tianjin", 39.14222, 117.17667), ("Beijing", 39.9075, 116.39723),
    ("Hohhot", 40.81056, 111.65222), ("Yinchuan", 38.46806, 106.27306),
]
_CHINANET_EDGES = [
    (0, 3), (0, 16), (0, 39), (1, 18), (1, 39), (2, 33), (4, 8), (5, 38),
    (6, 18), (6, 39), (7, 39), (8, 9), (8, 11), (8, 16), (8, 18), (8, 23),
    (8, 24), (8, 25), (8, 26), (8, 27), (8, 28), (8, 31), (8, 38), (8, 39),
    (9, 27), (10, 39), (12, 28), (13, 25), (14, 16), (14, 28), (15, 16),
    (15, 28), (16, 27), (16, 28), (17, 28), (18, 25), (18, 27), (18, 28),
    (18, 32), (18, 33), (18, 39), (18, 40), (18, 41), (19, 39), (20, 23),
    (21, 28), (22, 25), (22, 28), (23, 28), (23, 39), (25, 27), (25, 39),
    (27, 30), (27, 39), (28, 29), (28, 38), (28, 39), (32, 39), (33, 39),
    (34, 39), (35, 39), (36, 39), (37, 38), (38, 39), (39, 40), (39, 41),
]

# (label, lat, long) — public Internet Topology Zoo "Interoute" node list:
# the reference's LARGEST real scenario (configs/networks/interroute/,
# in4..in36 variants).  The Zoo source is a multigraph with parallel links
# and self-loops (110 nodes / 158 raw edges); deduplicated to the simple
# graph (146 edges) — parallel links carry identical caps so the simple
# graph preserves routing semantics.
_INTERROUTE_CITIES = [
    ("Bremen", 53.07516, 8.80777), ("Poznan", 52.41667, 16.96667),
    ("Pisa", 43.71553, 10.39659), ("Florence", 43.76667, 11.25),
    ("Udine", 46.06194, 13.24222), ("Graz", 47.06667, 15.45),
    ("Salzburg", 47.79941, 13.04399), ("Nuremberg", 49.44778, 11.06833),
    ("Leipzig", 51.33962, 12.37129), ("Dresden", 51.05089, 13.73832),
    ("London", 51.50853, -0.12574), ("Brussels", 50.85045, 4.34878),
    ("Stuttgart", 48.78232, 9.17702), ("Amsterdam", 52.37403, 4.88969),
    ("Moscow", 55.75222, 37.61556), ("Helsinki", 60.16952, 24.93545),
    ("Paris", 48.85341, 2.3488), ("Dubai", None, None),
    ("Frankfurt", 50.11667, 8.68333), ("Munich", 48.13743, 11.57549),
    ("Calais", 50.9581, 1.85205), ("Liege", 50.64119, 5.57178),
    ("Dublin", 53.34399, -6.26719), ("Slough", 51.5, -0.58333),
    ("Nancy", 48.68333, 6.2), ("Basle", 47.56667, 7.6),
    ("Karlsruhe", 49.00472, 8.38583), ("Strasbourg", 48.58333, 7.75),
    ("Berne", 46.94809, 7.44744), ("Lausanne", 46.516, 6.63282),
    ("PoP30", None, None), ("PoP31", None, None),
    ("Budapest", 47.49801, 19.03991), ("Vienna", 48.20849, 16.37208),
    ("Dusseldorf", 51.22172, 6.77616), ("Hamburg", 53.55, 10.0),
    ("PoP36", None, None), ("PoP37", None, None),
    ("Milan", 45.46427, 9.18951), ("Berlin", 52.52437, 13.41053),
    ("Sofia", 42.69751, 23.32415), ("Edirne", None, None),
    ("Bucharest", 44.43225, 26.10626), ("Timisoara", 45.74944, 21.22722),
    ("Stockholm", 59.33258, 18.0649), ("Brno", 49.19522, 16.60796),
    ("Cologne", 50.93333, 6.95), ("Bonn", 50.73333, 7.1),
    ("Venice", 45.43861, 12.32667), ("Bologna", 44.49381, 11.33875),
    ("Narbonne", 43.18333, 3.0), ("Bordeaux", 44.83333, -0.56667),
    ("Zurich", 47.36667, 8.55), ("Copenhagen", 55.67594, 12.56553),
    ("Turin", 45.07049, 7.68682), ("Genoa", 44.40632, 8.93386),
    ("Lyon", 45.75, 4.85), ("Marseille", 43.29695, 5.38107),
    ("Bruges", 51.20892, 3.22424), ("Gothenburg", 57.70716, 11.96679),
    ("Oslo", 59.91273, 10.74609), ("Zandvoort", 52.37487, 4.53409),
    ("Istanbul", 52.8557, 44.8332), ("Bari", 41.11773, 16.85118),
    ("Prague", 50.08804, 14.42076), ("Warsaw", 52.22977, 21.01178),
    ("Szolnok", 47.18333, 20.2), ("Krakow", 50.08333, 19.91667),
    ("Ruse", 43.85639, 25.97083), ("Szeged", 46.253, 20.14824),
    ("Pescara", 42.46024, 14.21021), ("Thessalonika", 40.64028, 22.94389),
    ("Lille", 50.63333, 3.06667), ("Luxembourg", 49.61167, 6.13),
    ("Bratislava", 48.14816, 17.10674), ("Hannover", 52.37052, 9.73322),
    ("Madrid", 40.4165, -3.70256), ("Geneva", 46.20222, 6.14569),
    ("Varna", 43.21667, 27.91667), ("Haskovo", 41.94028, 25.56944),
    ("Veliko Turnovo", 43.08124, 25.62904), ("Plovdiv", 42.15, 24.75),
    ("Washington DC", None, None), ("New York", 53.07897, -0.14008),
    ("Naples", 40.83333, 14.25), ("Mazara del Vallo", 37.66414, 12.58804),
    ("Valencia", 39.46975, -0.37739), ("Seville", 37.37722, -5.98694),
    ("Bilbao", 43.26271, -2.92528), ("Poitiers", 46.58333, 0.33333),
    ("Cagliari", 39.20738, 9.13462), ("Olbia", 40.92137, 9.48563),
    ("Nice", 43.70313, 7.26608), ("Toulouse", 43.60426, 1.44367),
    ("PoP95", None, None), ("Barcelona", 41.38879, 2.15899),
    ("East Africa", None, None), ("South Africa", None, None),
    ("Athens", None, None), ("Tunis", None, None),
    ("Malta", None, None), ("Rome", 41.89474, 12.4839),
    ("Essen", 51.45, 7.01667), ("Dortmund", 51.51667, 7.45),
    ("Utrecht", 52.09083, 5.12222), ("Rotterdam", 51.9225, 4.47917),
    ("Antwerp", 51.21667, 4.41667), ("Ghent", 51.05, 3.71667),
    ("Gibraltar", 36.14474, -5.35257), ("PoP109", None, None),
]
_INTERROUTE_EDGES = [
    (0, 35), (0, 103), (1, 39), (1, 65), (2, 3), (2, 55), (2, 101),
    (3, 49), (3, 101), (4, 5), (4, 48), (5, 33), (6, 19), (6, 33), (7, 8),
    (7, 18), (7, 19), (7, 64), (8, 9), (8, 64), (9, 39), (10, 17),
    (10, 22), (10, 31), (10, 37), (10, 82), (10, 83), (11, 21), (11, 72),
    (11, 73), (11, 106), (12, 19), (12, 26), (12, 27), (12, 52), (13, 61),
    (13, 104), (13, 105), (14, 15), (14, 44), (15, 44), (16, 24), (16, 27),
    (16, 56), (16, 72), (16, 89), (17, 23), (18, 26), (18, 27), (18, 47),
    (20, 31), (20, 72), (21, 46), (23, 31), (24, 27), (25, 28), (25, 52),
    (28, 29), (29, 77), (30, 84), (30, 85), (30, 99), (30, 100), (32, 33),
    (32, 43), (32, 66), (32, 74), (33, 45), (34, 46), (34, 102), (34, 104),
    (35, 53), (35, 75), (36, 39), (36, 53), (36, 75), (37, 58), (37, 61),
    (38, 48), (38, 49), (38, 52), (38, 54), (40, 41), (40, 68), (40, 71),
    (40, 80), (40, 81), (41, 62), (41, 68), (41, 71), (42, 43), (42, 68),
    (42, 79), (42, 109), (43, 69), (43, 78), (43, 79), (43, 81), (44, 53),
    (44, 60), (45, 64), (45, 67), (45, 74), (46, 47), (47, 73), (48, 49),
    (49, 70), (50, 51), (50, 57), (50, 93), (50, 95), (51, 88), (51, 89),
    (51, 93), (53, 59), (54, 55), (55, 92), (56, 57), (56, 77), (57, 92),
    (57, 96), (57, 97), (58, 107), (59, 60), (63, 70), (63, 71), (63, 84),
    (63, 98), (65, 67), (66, 109), (69, 109), (76, 87), (76, 88), (78, 80),
    (82, 83), (84, 101), (85, 90), (86, 94), (86, 95), (87, 94), (90, 91),
    (91, 101), (94, 108), (102, 103), (105, 106), (106, 107),
]


def _geo_zoo_network(cities, edge_list, num_ingress, link_cap,
                     node_cap_range, seed,
                     node_cap: float = 1.0) -> NetworkSpec:
    """Zoo network with per-link geodesic delay (3 ms default where a PoP
    has no coordinates, reader.py:212).  Node caps are random integers in
    [lo, hi) — the reference's rand-capL-H assets — or the fixed
    ``node_cap`` when ``node_cap_range`` is None (capK assets)."""
    rng = np.random.default_rng(seed)
    n = len(cities)
    if node_cap_range is None:
        caps = [float(node_cap)] * n
    else:
        caps = [float(rng.integers(*node_cap_range)) for _ in range(n)]
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = []
    for u, v in edge_list:
        _, lat1, lon1 = cities[u]
        _, lat2, lon2 = cities[v]
        if None in (lat1, lon1, lat2, lon2):
            delay = 3.0
        else:
            delay = geo_delay_ms(lat1, lon1, lat2, lon2)
        edges.append((u, v, link_cap, delay))
    return NetworkSpec(
        node_caps=caps, node_types=types, edges=edges,
        node_names=[c[0] for c in cities],
        coords=[(c[1] or 0.0, c[2] or 0.0) for c in cities])


def tinet(num_ingress: int = 2, link_cap: float = 1000.0,
          node_cap_range: Tuple[int, int] = (0, 3),
          seed: int = 0) -> NetworkSpec:
    """Tinet (Topology Zoo): 53 nodes / 89 edges — the reference's
    tinet-inK-rand-cap0-2 mid-size scenarios (ladder rung 4 entry)."""
    return _geo_zoo_network(_TINET_CITIES, _TINET_EDGES, num_ingress,
                            link_cap, node_cap_range, seed)


def chinanet(num_ingress: int = 2, link_cap: float = 1000.0,
             node_cap_range: Tuple[int, int] = (0, 3),
             seed: int = 0) -> NetworkSpec:
    """Chinanet (Topology Zoo): 42 nodes / 66 edges — the reference's
    chinanet-inK-rand-cap0-2 scenarios."""
    return _geo_zoo_network(_CHINANET_CITIES, _CHINANET_EDGES, num_ingress,
                            link_cap, node_cap_range, seed)


def interroute(num_ingress: int = 4, link_cap: float = 1000.0,
               node_cap_range: Tuple[int, int] = (0, 3),
               seed: int = 0) -> NetworkSpec:
    """Interoute (Topology Zoo): 110 nodes / 146 simple edges — the
    reference's largest real scenario (interroute-inK-rand-cap0-2),
    BASELINE ladder rung 5 scale."""
    return _geo_zoo_network(_INTERROUTE_CITIES, _INTERROUTE_EDGES,
                            num_ingress, link_cap, node_cap_range, seed)


def triangle(node_caps: Sequence[float] = (10.0, 10.0, 10.0),
             link_cap: float = 100.0, link_delay: float = 1.0,
             num_ingress: int = 1) -> NetworkSpec:
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(3)]
    edges = [(0, 1, link_cap, link_delay), (1, 2, link_cap, link_delay),
             (0, 2, link_cap, link_delay)]
    return NetworkSpec(node_caps=list(node_caps), node_types=types, edges=edges)


def line(n: int = 3, node_cap: float = 10.0, link_cap: float = 100.0,
         link_delay: float = 1.0, num_ingress: int = 1) -> NetworkSpec:
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = [(i, i + 1, link_cap, link_delay) for i in range(n - 1)]
    return NetworkSpec(node_caps=[node_cap] * n, node_types=types, edges=edges)


def star(n: int = 6, node_cap: float = 10.0, link_cap: float = 100.0,
         link_delay: float = 1.0, num_ingress: int = 1) -> NetworkSpec:
    """Hub-and-spoke: node 0 is the hub, nodes 1..n-1 hang off it — the
    maximal-contention shape (every path crosses the hub)."""
    if n < 2:
        raise ValueError(f"star needs >= 2 nodes, got {n}")
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = [(0, i, link_cap, link_delay) for i in range(1, n)]
    return NetworkSpec(node_caps=[node_cap] * n, node_types=types,
                       edges=edges)


def ring(n: int = 6, node_cap: float = 10.0, link_cap: float = 100.0,
         link_delay: float = 1.0, num_ingress: int = 1) -> NetworkSpec:
    """Cycle of n nodes — two disjoint paths between any pair, the
    smallest shape where routing has a real choice."""
    if n < 3:
        raise ValueError(f"ring needs >= 3 nodes, got {n}")
    types = ["Ingress" if i < num_ingress else "Normal" for i in range(n)]
    edges = [(i, (i + 1) % n, link_cap, link_delay) for i in range(n)]
    return NetworkSpec(node_caps=[node_cap] * n, node_types=types,
                       edges=edges)


def two_node(node_caps: Sequence[float] = (5.0, 5.0), link_cap: float = 100.0,
             link_delay: float = 1.0) -> NetworkSpec:
    return NetworkSpec(node_caps=list(node_caps),
                       node_types=["Ingress", "Normal"],
                       edges=[(0, 1, link_cap, link_delay)])


def random_network(n_nodes: int, avg_degree: float = 2.5,
                   node_cap_range: Tuple[int, int] = (1, 4),
                   link_cap: float = 1000.0,
                   delay_range: Tuple[float, float] = (1.0, 10.0),
                   num_ingress: int = 4, seed: int = 0) -> NetworkSpec:
    """Random connected topology, the programmatic analogue of the
    gen_networks.py-mutated training sets (scripts/gen_networks.py +
    BASELINE config 4: 64-128 node randomized topologies)."""
    rng = np.random.default_rng(seed)
    caps = [float(rng.integers(*node_cap_range)) for _ in range(n_nodes)]
    ing = rng.choice(n_nodes, size=min(num_ingress, n_nodes), replace=False)
    types = ["Ingress" if i in ing else "Normal" for i in range(n_nodes)]
    edges: List[Tuple[int, int, float, float]] = []
    seen = set()

    def add(u, v):
        if u != v and (u, v) not in seen and (v, u) not in seen:
            seen.add((u, v))
            edges.append((u, v, link_cap, float(np.around(rng.uniform(*delay_range)))))

    # random spanning tree first (guarantees connectivity)
    perm = rng.permutation(n_nodes)
    for i in range(1, n_nodes):
        add(int(perm[rng.integers(0, i)]), int(perm[i]))
    target_edges = min(int(avg_degree * n_nodes / 2),
                       n_nodes * (n_nodes - 1) // 2)
    while len(edges) < target_edges:
        add(int(rng.integers(n_nodes)), int(rng.integers(n_nodes)))
    return NetworkSpec(node_caps=caps, node_types=types, edges=edges)


def mutate_caps(spec: NetworkSpec, cap_range: Tuple[int, int],
                seed: int = 0) -> NetworkSpec:
    """Rewrite node caps with random values (gen_networks.py:6-21)."""
    rng = np.random.default_rng(seed)
    return NetworkSpec(
        node_caps=[float(rng.integers(*cap_range)) for _ in spec.node_caps],
        node_types=list(spec.node_types), edges=list(spec.edges),
        node_names=list(spec.node_names),
        coords=list(spec.coords) if spec.coords else None)


def set_ingress(spec: NetworkSpec, nodes: Sequence[int]) -> NetworkSpec:
    """Mark the given nodes as Ingress (gen_networks.py:24-38)."""
    types = ["Ingress" if i in set(nodes) else t
             for i, t in enumerate(spec.node_types)]
    return NetworkSpec(node_caps=list(spec.node_caps), node_types=types,
                       edges=list(spec.edges), node_names=list(spec.node_names),
                       coords=list(spec.coords) if spec.coords else None)


def write_graphml(spec: NetworkSpec, path: str) -> None:
    """Persist a NetworkSpec as a reference-compatible GraphML asset."""
    import networkx as nx

    g = nx.Graph()
    for i, cap in enumerate(spec.node_caps):
        attrs = dict(NodeCap=cap, NodeType=spec.node_types[i])
        if spec.node_names:
            attrs["label"] = spec.node_names[i]
        if spec.coords:
            attrs["Latitude"], attrs["Longitude"] = spec.coords[i]
        g.add_node(i, **attrs)
    for u, v, cap, delay in spec.edges:
        g.add_edge(u, v, LinkFwdCap=cap, LinkDelay=delay)
    nx.write_graphml(g, path)
