"""Compiled-HLO structure metrics — the op-count perf proxy.

The substep is op-COUNT bound (BENCH_NOTES round-5 roofline: ~60 small
fusions at ~30 µs apiece, ~100x above the HBM roof), so the number of
fusion computations in the compiled executable is the cheapest faithful
proxy for its per-call overhead — countable on any backend, no chip
window needed.  It exists as a GATE because bit-exactness alone is not
enough: the rejected round-5 scatter-merge was bit-exact yet REGRESSED
281 -> 294 fusions and lost throughput; a fusion-count check would have
rejected it before the chip ever saw it.

Consumers: ``tools/profile_substep.py --mfu`` (per-rung roofline rows),
``tools/lever_sweep.py`` (per-cell rows), and the tier-1 fusion-budget
regression test (``tests/test_megakernel.py``), which pins the compiled
flagship-interval ``engine.apply`` count on the CPU backend and asserts
the pallas megakernel path stays strictly below the XLA path.

Stdlib-only on purpose (the gsc-lint convention for analysis/): the
argument is an already-compiled jax ``Compiled`` object (or its
``as_text()`` dump) — this module never imports jax.
"""
from __future__ import annotations

__all__ = ["count_fusions", "count_ops", "hlo_text", "op_histogram"]


def hlo_text(compiled_or_text) -> str:
    """Post-optimization HLO text of a ``jax`` ``Compiled`` object (the
    result of ``jit(f).lower(*args).compile()``); strings pass through."""
    if isinstance(compiled_or_text, str):
        return compiled_or_text
    return compiled_or_text.as_text()


def count_fusions(compiled_or_text) -> int:
    """Number of fusion computations in the compiled executable.

    Counts ``" fusion("`` instruction sites in the post-optimization HLO
    — fusion *calls*, including those inside while-loop bodies (an
    ``lax.scan`` body compiles once, so a per-substep op costs one count,
    not one per iteration).  Comparisons are only meaningful at a fixed
    jaxlib version and backend; the budget test re-measures both sides of
    its assertion in the same process for exactly that reason.
    """
    return hlo_text(compiled_or_text).count(" fusion(")


def count_ops(compiled_or_text, op: str) -> int:
    """Occurrences of an HLO op (e.g. ``"while"``, ``"gather"``,
    ``"scatter"``, ``"dot"``) in the compiled executable — the drill-down
    companion to :func:`count_fusions` (a CPU scatter lowers to a serial
    ``while``, a fact the megakernel work keeps re-learning)."""
    return hlo_text(compiled_or_text).count(f" {op}(")


def op_histogram(compiled_or_text, ops) -> dict:
    """``{op: count}`` over a list of HLO op names, one text pass per op —
    the batch form the cost ledger (obs.perf) stores per entry point."""
    text = hlo_text(compiled_or_text)
    return {op: text.count(f" {op}(") for op in ops}
