"""Compiled-HLO structure metrics — the op-count perf proxy.

The substep is op-COUNT bound (BENCH_NOTES round-5 roofline: ~60 small
fusions at ~30 µs apiece, ~100x above the HBM roof), so the number of
fusion computations in the compiled executable is the cheapest faithful
proxy for its per-call overhead — countable on any backend, no chip
window needed.  It exists as a GATE because bit-exactness alone is not
enough: the rejected round-5 scatter-merge was bit-exact yet REGRESSED
281 -> 294 fusions and lost throughput; a fusion-count check would have
rejected it before the chip ever saw it.

Consumers: ``tools/profile_substep.py --mfu`` (per-rung roofline rows),
``tools/lever_sweep.py`` (per-cell rows), and the tier-1 fusion-budget
regression test (``tests/test_megakernel.py``), which pins the compiled
flagship-interval ``engine.apply`` count on the CPU backend and asserts
the pallas megakernel path stays strictly below the XLA path.

Stdlib-only on purpose (the gsc-lint convention for analysis/): the
argument is an already-compiled jax ``Compiled`` object (or its
``as_text()`` dump) — this module never imports jax.
"""
from __future__ import annotations

import re

__all__ = ["collective_stats", "count_fusions", "count_ops", "hlo_text",
           "op_histogram"]


def hlo_text(compiled_or_text) -> str:
    """Post-optimization HLO text of a ``jax`` ``Compiled`` object (the
    result of ``jit(f).lower(*args).compile()``); strings pass through."""
    if isinstance(compiled_or_text, str):
        return compiled_or_text
    return compiled_or_text.as_text()


def count_fusions(compiled_or_text) -> int:
    """Number of fusion computations in the compiled executable.

    Counts ``" fusion("`` instruction sites in the post-optimization HLO
    — fusion *calls*, including those inside while-loop bodies (an
    ``lax.scan`` body compiles once, so a per-substep op costs one count,
    not one per iteration).  Comparisons are only meaningful at a fixed
    jaxlib version and backend; the budget test re-measures both sides of
    its assertion in the same process for exactly that reason.
    """
    return hlo_text(compiled_or_text).count(" fusion(")


def count_ops(compiled_or_text, op: str) -> int:
    """Occurrences of an HLO op (e.g. ``"while"``, ``"gather"``,
    ``"scatter"``, ``"dot"``) in the compiled executable — the drill-down
    companion to :func:`count_fusions` (a CPU scatter lowers to a serial
    ``while``, a fact the megakernel work keeps re-learning)."""
    return hlo_text(compiled_or_text).count(f" {op}(")


def op_histogram(compiled_or_text, ops) -> dict:
    """``{op: count}`` over a list of HLO op names, one text pass per op —
    the batch form the cost ledger (obs.perf) stores per entry point."""
    text = hlo_text(compiled_or_text)
    return {op: text.count(f" {op}(") for op in ops}


#: the cross-device movers a partitioned program can contain — the
#: interconnect cost the `tp` rulebook spends bit-equality to reduce.
#: Async forms (``all-reduce-start``/``-done``) count as ONE op on the
#: ``-start`` side (the ``-done`` is the same transfer completing).
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "all-to-all", "collective-permute")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# every `dtype[dims]` occurrence in an HLO result type, tuple results
# included: `(f32[4,8]{1,0}, f32[4]{0})`
_SHAPE_RE = re.compile(r"([a-z]\d*[a-z0-9]*|pred)\[([0-9,]*)\]")


def _shape_bytes(type_text: str, largest_only: bool = False) -> int:
    """Payload bytes of an HLO result-type string: the sum over tuple
    elements, or with ``largest_only`` just the biggest one — async
    ``-start`` forms return ``(operand, result)`` tuples, where summing
    would double-count the transfer (the result is the payload; for
    all-gather it is the larger element, for all-reduce both are
    equal)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(type_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * size)
    if not sizes:
        return 0
    return max(sizes) if largest_only else sum(sizes)


def collective_stats(compiled_or_text, ops=COLLECTIVE_OPS) -> dict:
    """Per-collective count + payload bytes of a compiled executable:
    ``{"ops": {op: {"count", "bytes"}}, "count": total, "bytes": total}``.

    Bytes are summed over each collective instruction's RESULT shape
    (the text between ``=`` and the op name — operand shapes inside the
    parens never match), so an ``all-gather`` counts its gathered output
    and an ``all-reduce`` its reduced tensor.  This is a per-call
    *payload* figure, not wire traffic (a ring all-reduce moves
    ~2x(n-1)/n of it per hop) — stable across backends, which is what a
    tp-vs-sharded interconnect comparison needs.  Ops inside while-loop
    bodies count once per program, same convention as
    :func:`count_fusions`."""
    text = hlo_text(compiled_or_text)
    per_op = {op: {"count": 0, "bytes": 0} for op in ops}
    for line in text.splitlines():
        # `head` holds the instruction name only; the result type leads
        # the right-hand side, before the op token
        head, eq, rhs = line.partition("=")
        if not eq:
            continue
        for op in ops:
            idx, is_start = -1, False
            for token, start in ((f" {op}(", False),
                                 (f" {op}-start(", True)):
                idx = rhs.find(token)
                if idx >= 0:
                    is_start = start
                    break
            if idx < 0:
                continue
            rec = per_op[op]
            rec["count"] += 1
            rec["bytes"] += _shape_bytes(rhs[:idx],
                                         largest_only=is_start)
            break
    present = {op: rec for op, rec in per_op.items() if rec["count"]}
    return {"ops": present,
            "count": sum(r["count"] for r in present.values()),
            "bytes": sum(r["bytes"] for r in present.values())}
