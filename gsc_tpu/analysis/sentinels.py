"""Runtime sentinels: compile/retrace monitoring + host-sync guarding.

The static pass (astlint.py) over-approximates; these sentinels make the
same properties *testable at runtime*:

- :class:`CompileMonitor` hooks JAX's compile logging (the
  ``jax.log_compiles`` channel on the ``jax._src.dispatch`` logger) and
  counts traces / XLA compilations per jitted entry point.  Wired into a
  :class:`~gsc_tpu.obs.MetricsHub` it emits one ``compile`` event per
  watched entry point into the run's ``events.jsonl`` (rendered by
  ``tools/obs_report.py``), so a retrace storm is visible in run
  telemetry, not just in wall time.  Counting keys on TRACES, not backend
  compiles: the persistent compilation cache (tests/conftest.py) can skip
  the backend step, but a cache-missing jit call always re-traces.
- :func:`assert_no_retrace` — context manager that fails loudly when a
  watched entry point traces during the guarded region (the steady-state
  contract of the pipelined episode loop).
- :func:`no_host_sync` — wraps ``jax.transfer_guard_device_to_host`` so a
  guarded region performs ZERO unplanned device->host transfers; the
  XLA error is re-raised as :class:`HostSyncError` naming the region.

The monitor swallows the raw ``log_compiles`` WARNING spam while active
(the structured events replace it) and restores the previous logging /
config state on stop.
"""
from __future__ import annotations

import logging
import re
import threading
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Tuple

# entry points a training run cares about: the fused episode/chunk kernels
# and their two-call fallbacks (agents/ddpg.py, parallel/dp.py, env reset),
# plus the on-device scenario sampler (topology/factory.py) and the async
# replay service insert (parallel/async_rl.py) — a factory or async run's
# stream contract is exactly one trace per entry point
DEFAULT_WATCH = ("episode_step", "rollout_episode", "learn_burst",
                 "chunk_step", "rollout_episodes", "reset_all", "reset",
                 "step", "factory_sample", "replay_ingest")

_TRACE_RE = re.compile(
    r"Finished tracing \+ transforming (.+?) for pjit in ([0-9.eE+-]+) sec")
_XLA_RE = re.compile(
    r"Finished XLA compilation of jit\((.+?)\) in ([0-9.eE+-]+) sec")
_SWALLOW_PREFIXES = ("Finished tracing + transforming",
                     "Finished jaxpr to MLIR module conversion",
                     "Finished XLA compilation of", "Compiling ")


class RetraceError(AssertionError):
    """A watched jitted entry point re-traced inside a no-retrace region."""


class HostSyncError(AssertionError):
    """A guarded region performed a device->host transfer."""


class _CompileLogTap(logging.Filter):
    """ONE process-wide tap on the jax compile-log loggers, fanning each
    parsed record out to every active monitor.

    A per-monitor filter would blind stacked monitors:
    ``logging.Filterer.filter`` short-circuits on the first filter
    returning False, so a suppressing observer-owned monitor would
    swallow every record before a later-installed ``assert_no_retrace``
    monitor saw it.  Suppression is therefore decided ACROSS all active
    monitors, after all of them have counted the record."""

    def __init__(self):
        super().__init__()
        self.monitors: List["CompileMonitor"] = []   # guarded by _TAP_LOCK

    def filter(self, record: logging.LogRecord) -> bool:
        msg = record.getMessage()
        parsed = None
        m = _TRACE_RE.search(msg)
        if m:
            parsed = (m.group(1), "trace", float(m.group(2)))
        else:
            m = _XLA_RE.search(msg)
            if m:
                parsed = (m.group(1), "xla", float(m.group(2)))
        with _TAP_LOCK:
            monitors = list(self.monitors)
        if parsed is not None:
            for mon in monitors:
                mon._on_event(*parsed)
        if msg.startswith(_SWALLOW_PREFIXES) and any(
                mon.suppress_logs for mon in monitors):
            return False
        return True


_TAP = _CompileLogTap()
_TAP_LOCK = threading.Lock()
_PREV_LOG_COMPILES = [None]   # jax_log_compiles value before the first tap


def _register_monitor(mon: "CompileMonitor"):
    import jax

    with _TAP_LOCK:
        if not _TAP.monitors:
            for name in CompileMonitor._LOGGERS:
                logging.getLogger(name).addFilter(_TAP)
            _PREV_LOG_COMPILES[0] = jax.config.jax_log_compiles
            jax.config.update("jax_log_compiles", True)
        _TAP.monitors.append(mon)


def _unregister_monitor(mon: "CompileMonitor"):
    import jax

    with _TAP_LOCK:
        if mon in _TAP.monitors:
            _TAP.monitors.remove(mon)
        if not _TAP.monitors:
            jax.config.update("jax_log_compiles", _PREV_LOG_COMPILES[0])
            for name in CompileMonitor._LOGGERS:
                logging.getLogger(name).removeFilter(_TAP)


class CompileMonitor:
    """Counts jit traces / XLA compiles per function name while active.

    ``hub`` (a :class:`gsc_tpu.obs.MetricsHub`) is optional: with one,
    every trace/compile of a *watched* name emits a structured ``compile``
    event (the events.jsonl stream) plus ``jit_traces_total`` /
    ``jit_compiles_total{fn=...}`` counters; unwatched names only bump an
    aggregate ``jit_traces_other_total`` counter so tiny ``jnp`` op jits
    cannot flood the stream.  ``watch=None`` watches everything.
    """

    _LOGGERS = ("jax._src.dispatch", "jax._src.interpreters.pxla")

    def __init__(self, hub=None, watch: Optional[Iterable[str]] =
                 DEFAULT_WATCH, suppress_logs: bool = True):
        self.hub = hub
        self.watch = None if watch is None else set(watch)
        self.suppress_logs = suppress_logs
        self._lock = threading.Lock()
        self.trace_counts: Dict[str, int] = {}
        self.compile_counts: Dict[str, int] = {}
        # bounded: the durable record is the hub's events.jsonl stream;
        # this window only serves tests/interactive inspection, and a
        # retrace storm on a long run must not grow host memory with it
        self.events: deque = deque(maxlen=1024)
        # set when the start-time self-probe saw no trace record: the
        # jax log wording drifted and the monitor is blind.  Observability
        # paths log-and-continue; assert_no_retrace fails CLOSED on it.
        self.degraded = False
        self._started = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "CompileMonitor":
        if self._started:
            return self
        self._started = True
        _register_monitor(self)
        self._self_probe()
        return self

    def _self_probe(self):
        """Jit a throwaway function and check its trace was counted.  The
        regexes are pinned to jax's log_compiles wording; on format drift
        the monitor would otherwise count nothing and every no-retrace
        assertion would pass vacuously — fail loudly instead."""
        import jax

        def _gsc_compile_probe(x):   # fresh object every start: re-traces
            return x

        try:
            jax.jit(_gsc_compile_probe)(0)
        except Exception:   # no backend available: leave degraded unset
            return
        if self.traces("_gsc_compile_probe") == 0:
            self.degraded = True
            logging.getLogger("gsc_tpu.analysis").warning(
                "CompileMonitor self-probe saw no trace record — the jax "
                "log_compiles message format has drifted; compile events "
                "and retrace detection are BLIND until the sentinel "
                "regexes are updated")

    def stop(self):
        if not self._started:
            return
        self._started = False
        _unregister_monitor(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ recording
    def _watched(self, fn: str) -> bool:
        return self.watch is None or fn in self.watch

    def _on_event(self, fn: str, kind: str, duration_s: float):
        with self._lock:
            counts = (self.trace_counts if kind == "trace"
                      else self.compile_counts)
            counts[fn] = counts.get(fn, 0) + 1
            n = counts[fn]
            if self._watched(fn):
                self.events.append({"fn": fn, "kind": kind,
                                    "duration_s": duration_s, "count": n})
        if self.hub is None:
            return
        if self._watched(fn):
            name = ("jit_traces_total" if kind == "trace"
                    else "jit_compiles_total")
            self.hub.counter(name, fn=fn)
            # field is `stage` (trace|xla), not `kind` — MetricsHub.event's
            # first parameter owns that name
            self.hub.event("compile", fn=fn, stage=kind,
                           duration_s=round(duration_s, 4), count=n)
        elif kind == "trace":
            self.hub.counter("jit_traces_other_total")

    # ------------------------------------------------------------- queries
    def snapshot(self) -> Dict[str, Tuple[int, int]]:
        """{fn: (traces, xla_compiles)} for every name seen so far."""
        with self._lock:
            names = set(self.trace_counts) | set(self.compile_counts)
            return {n: (self.trace_counts.get(n, 0),
                        self.compile_counts.get(n, 0)) for n in names}

    def traces(self, fn: str) -> int:
        with self._lock:
            return self.trace_counts.get(fn, 0)

    @contextmanager
    def assert_no_retrace(self, *names: str):
        """Fail with :class:`RetraceError` if any of ``names`` (default:
        the watch set) traces inside the region — the steady-state
        pipelined loop must compile each entry point exactly once, before
        this guard begins."""
        if self.degraded:
            raise RetraceError(
                "CompileMonitor is degraded (log-format drift: the "
                "start-time self-probe saw no trace record) — a "
                "no-retrace assertion would pass vacuously; update the "
                "sentinel regexes for this jax version")
        watched = set(names) or (self.watch or set())
        with self._lock:
            before = {n: self.trace_counts.get(n, 0) for n in watched} \
                if watched else dict(self.trace_counts)
        yield self
        with self._lock:
            after = {n: self.trace_counts.get(n, 0)
                     for n in (watched or self.trace_counts)}
        grew = {n: after.get(n, 0) - before.get(n, 0)
                for n in after if after.get(n, 0) > before.get(n, 0)}
        if grew:
            detail = ", ".join(f"{n} (+{k})" for n, k in sorted(grew.items()))
            raise RetraceError(
                f"jitted entry point(s) re-traced inside a no-retrace "
                f"region: {detail} — check for weak-type scalars, "
                "changing shapes, or fresh static args in the hot loop")


@contextmanager
def assert_no_retrace(*names: str, hub=None):
    """Standalone guard: monitors compiles only for the duration of the
    region and raises :class:`RetraceError` on any trace of ``names``
    (any trace at all when no names are given)."""
    mon = CompileMonitor(hub=hub, watch=set(names) or None)
    with mon:
        with mon.assert_no_retrace(*names):
            yield mon


@contextmanager
def no_host_sync(what: str = "guarded region"):
    """Zero unplanned device->host syncs inside the region.

    Two layers, because they catch different things on different
    backends:

    - ``jax.transfer_guard_device_to_host("disallow")`` — the XLA-level
      guard, authoritative on TPU/GPU where device buffers live off-host.
      On the CPU backend it is INERT (host-resident buffers convert
      zero-copy, no transfer is recorded), which is exactly where CI
      runs, hence:
    - a Python tripwire over the repo's host-sync entry points —
      ``np.asarray``/``np.array`` on a ``jax.Array``, ``jax.device_get``
      and ``jax.block_until_ready`` raise :class:`HostSyncError`
      immediately.  These are the R1 call forms (astlint) and cover every
      planned sync in the trainer/harness drain paths, so one sneaking
      into a dispatch region fails on any backend.  ``float()``/
      ``int()`` on a 0-d array cannot be intercepted from Python —
      that residual is the static pass's job.

    The numpy patch is process-global for the duration (raises only for
    jax.Array arguments) — test-scoped usage only, not for threaded
    production paths.  Host->device transfers (staging np.int32 args,
    prefetched traffic) remain allowed: the episode-loop contract is
    about the *device->host* syncs that serialize the pipeline."""
    import jax
    import numpy as np

    def _holds_jax_array(a):
        # containers sync too: np.asarray([stats["x"], stats["y"]]) is a
        # device->host materialization of every jax leaf inside
        try:
            return any(isinstance(leaf, jax.Array)
                       for leaf in jax.tree_util.tree_leaves(a))
        except Exception:   # unflattenable exotic object: not ours
            return False

    def _np_tripwire(name, orig):
        def wrapper(a, *args, **kwargs):
            if _holds_jax_array(a):
                raise HostSyncError(
                    f"{name}() materialized a jax.Array inside {what} — "
                    "an unplanned device->host sync")
            return orig(a, *args, **kwargs)
        return wrapper

    def _always_tripwire(name):
        def wrapper(*args, **kwargs):
            raise HostSyncError(
                f"{name}() inside {what} — an unplanned device->host "
                "sync")
        return wrapper

    patches = [
        (np, "asarray", _np_tripwire("np.asarray", np.asarray)),
        (np, "array", _np_tripwire("np.array", np.array)),
        (jax, "device_get", _always_tripwire("jax.device_get")),
        (jax, "block_until_ready",
         _always_tripwire("jax.block_until_ready")),
    ]
    saved = [(mod, name, getattr(mod, name)) for mod, name, _ in patches]
    for mod, name, repl in patches:
        setattr(mod, name, repl)
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    except HostSyncError:
        raise
    except Exception as e:  # noqa: BLE001 - classify, then re-raise
        msg = str(e)
        if "transfer" in msg.lower() and "disallow" in msg.lower():
            raise HostSyncError(
                f"unplanned device->host transfer inside {what}: {msg}"
            ) from e
        raise
    finally:
        for mod, name, orig in saved:
            setattr(mod, name, orig)
