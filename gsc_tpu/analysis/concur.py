"""Concurrency-discipline analysis for gsc-lint (rules R6-R10).

The stack is genuinely multi-threaded — async actor/learner fleet,
serving dispatcher, obs drainers, watchdog — and PR 18 had to diagnose a
collective-rendezvous deadlock (two threads interleaving per-device
enqueue order) by hand before inventing ``dispatch_lock``.  These rules
make that bug class, and its relatives, fail the lint gate instead:

- **R6 lock-order cycle** — a per-module lock-acquisition graph is built
  from ``with <lock>:`` nesting and ``.acquire()``/``.release()`` pairs;
  two functions that take the same pair of locks in opposite orders form
  a cycle, and every edge on a cycle is reported.  Locks are identified
  by attribute path (``self.flush_lock`` scoped to its class,
  ``ParallelDDPG.dispatch_lock``, bare closure locks scoped to their
  outermost function), so two classes' unrelated ``self._lock`` fields
  never alias.
- **R7 guarded-by** — a field whose ``__init__`` assignment carries a
  ``# guarded-by: <lock>`` comment may only be read or written inside a
  ``with`` on that lock (or in a method annotated
  ``# requires-lock: <lock>`` on its ``def`` line, which asserts the
  caller holds it).  ``__init__`` itself is exempt: construction happens
  before any thread can see the object.
- **R8 dispatch-without-lock** — in a module that spawns threads, every
  call to a multi-device dispatch entry point (``chunk_step`` /
  ``rollout_episodes`` / ``learn_burst`` / ``replay_ingest``) must be
  lexically under a ``dispatch_lock``.  This is the PR 18 deadlock as a
  rule: XLA's multi-device execution rendezvouses all partitions, so two
  threads whose per-device enqueue orders interleave inconsistently both
  wedge forever (see parallel/dp.py).  Call sites protected by a lock
  INSIDE the callee (the sharded wrappers) carry an inline disable
  naming that invariant.
- **R9 blocking-under-lock** — while lexically holding a lock: untimed
  ``queue.get()`` / ``.wait()`` / ``.join()`` / ``.result()``, a nested
  manual ``.acquire()``, ``block_until_ready``, or a device call
  (``run_batch``).  The one deliberate case — continuous batching holds
  ``flush_lock`` across the device call so weight swaps serialize
  against in-flight dispatches — carries an inline disable with its
  reason next to the code.
- **R10 thread-ctor discipline** — every ``threading.Thread(...)`` must
  pass ``name=`` and ``daemon=True``: the watchdog's stall events and
  blackbox.json post-mortems identify threads BY NAME, and an unnamed
  thread renders as ``Thread-N``; a non-daemon worker turns any crashed
  run into a hang at interpreter exit.

Everything is lexical (stdlib ``ast``, no dataflow): a lock is anything
``with``-entered or ``.acquire()``d whose final path segment is lock-ish
(``lock`` / ``_lock`` / ``_cond`` / ``mutex`` / ``_sem``); lambda bodies
and nested ``def``s are walked with an EMPTY held set because they
execute later, usually on another thread.  False positives go to the
baseline or an inline disable with a written reason — the same contract
as R1-R5.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

# final-path-segment heuristic for "this object is a lock"
_LOCKISH_EXACT = {"lock", "cond", "mutex"}
_LOCKISH_SUFFIX = ("_lock", "_cond", "_mutex", "_sem", "_semaphore")
_LOCKISH_PREFIX = ("lock_", "cond_")

# R8: the multi-device dispatch entry points (the donated_jit /
# pjit-sharded names from DONATED_SIGS plus the async learner's ingest)
DISPATCH_NAMES = {"chunk_step", "rollout_episodes", "learn_burst",
                  "replay_ingest"}
# the lock R8 requires (matched on the final path segment, so
# `self.dispatch_lock`, `pddpg.dispatch_lock` and a bare closure
# `dispatch_lock` all satisfy it)
DISPATCH_LOCK_NAME = "dispatch_lock"

# R9: calls that hand the device (or another thread) control while the
# holder keeps its lock
_DEVICE_CALL_NAMES = {"run_batch", "block_until_ready"}
_UNTIMED_BLOCKING_ATTRS = {"get", "wait", "join", "result"}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
_REQUIRES_LOCK_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_][\w.]*)")


def _is_lockish(name: str) -> bool:
    n = name.lower()
    return (n in _LOCKISH_EXACT or n.endswith(_LOCKISH_SUFFIX)
            or n.startswith(_LOCKISH_PREFIX))


def _dotted(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _trailing_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


# ------------------------------------------------------------- lock naming

@dataclass
class _FnScope:
    """Naming context for one function: which class `self` binds to and
    which outermost function scopes its bare closure locks."""
    qualname: str
    node: ast.AST
    owning_class: Optional[str]    # innermost enclosing class name
    scope_root: str                # outermost enclosing function qualname
    requires: List[str] = field(default_factory=list)  # requires-lock paths


def _lock_id(parts: List[str], scope: _FnScope,
             class_names: Set[str]) -> str:
    """Canonical identity of a lock path.  `self.X` is scoped to the
    owning class (two classes' `self._lock` must not alias), bare names
    to their outermost function (closure locks are shared across nested
    defs), `Class.X` to that class, and other `obj.X` chains to a
    module-wide `*.X` (the object's class is unknown)."""
    if len(parts) == 1:
        return f"{scope.scope_root}:{parts[0]}" if scope.scope_root \
            else parts[0]
    if parts[0] == "self" and scope.owning_class:
        return f"{scope.owning_class}.{'.'.join(parts[1:])}"
    if parts[0] in class_names:
        return ".".join(parts)
    return f"*.{parts[-1]}"


@dataclass
class _Held:
    lock_id: str
    text: str          # as written, for messages
    node: ast.AST      # acquisition site
    manual: bool = False   # .acquire() (vs `with`) — released by name


# ------------------------------------------------------------- the walker

class _FnWalker:
    """Source-order walk of one function body tracking the lexically held
    lock stack; emits acquisition edges (R6), attribute accesses (R7) and
    calls (R8/R9/R10) annotated with the held set at that point."""

    def __init__(self, scope: _FnScope, class_names: Set[str],
                 base_held: Sequence[_Held]):
        self.scope = scope
        self.class_names = class_names
        self.held: List[_Held] = list(base_held)
        self.edges: List[Tuple[_Held, _Held]] = []     # (outer, inner)
        self.accesses: List[Tuple[ast.Attribute, Tuple[str, ...]]] = []
        self.calls: List[Tuple[ast.Call, Tuple[str, ...],
                               Tuple[str, ...]]] = []

    # -- helpers
    def _lock_of(self, expr: ast.AST) -> Optional[Tuple[str, str]]:
        parts = _dotted(expr)
        if parts and _is_lockish(parts[-1]):
            return (_lock_id(parts, self.scope, self.class_names),
                    ".".join(parts))
        return None

    def _acquire(self, lock_id: str, text: str, node: ast.AST,
                 manual: bool) -> _Held:
        h = _Held(lock_id, text, node, manual)
        for outer in self.held:
            self.edges.append((outer, h))
        self.held.append(h)
        return h

    def _release(self, lock_id: str):
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i].manual and self.held[i].lock_id == lock_id:
                del self.held[i]
                return

    def _held_ids(self) -> Tuple[str, ...]:
        return tuple(h.lock_id for h in self.held)

    def _held_texts(self) -> Tuple[str, ...]:
        return tuple(h.text for h in self.held)

    # -- statements
    def walk(self):
        self._stmts(getattr(self.scope.node, "body", []))

    def _stmts(self, body: Sequence[ast.stmt]):
        for st in body:
            self._stmt(st)

    def _stmt(self, st: ast.stmt):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return      # nested defs run later — separate walk, empty held
        if isinstance(st, (ast.With, ast.AsyncWith)):
            pushed: List[_Held] = []
            for item in st.items:
                self._expr(item.context_expr)
                lk = self._lock_of(item.context_expr)
                if lk:
                    pushed.append(self._acquire(
                        lk[0], lk[1], item.context_expr, manual=False))
            self._stmts(st.body)
            for h in pushed:
                if h in self.held:
                    self.held.remove(h)
        elif isinstance(st, ast.If):
            self._expr(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.While):
            self._expr(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
        else:
            self._expr(st)

    # -- expressions (source order; lambdas/nested defs excluded)
    def _expr(self, node: Optional[ast.AST]):
        if node is None:
            return
        for child in self._iter_own(node):
            if isinstance(child, ast.Call):
                self._call(child)
            elif isinstance(child, ast.Attribute):
                self.accesses.append((child, self._held_ids()))

    def _iter_own(self, node: ast.AST):
        """Pre-order walk excluding nested def/class/lambda subtrees
        (deferred execution does not inherit the lexical held set)."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(reversed(list(ast.iter_child_nodes(n))))

    def _call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                       "release"):
            lk = self._lock_of(f.value)
            if lk:
                if f.attr == "acquire":
                    # record the call (R9 sees a nested acquire) BEFORE
                    # the lock joins the held set
                    self.calls.append((node, self._held_ids(),
                                       self._held_texts()))
                    self._acquire(lk[0], lk[1], node, manual=True)
                else:
                    self._release(lk[0])
                return
        self.calls.append((node, self._held_ids(), self._held_texts()))


# ------------------------------------------------------------ module scan

def _collect_scopes(tree: ast.Module,
                    lines: List[str]) -> Tuple[List[_FnScope], Set[str]]:
    """Every function in the module with its lock-naming context, plus
    the set of class names (for `Class.lock` identities)."""
    scopes: List[_FnScope] = []
    class_names: Set[str] = set()

    def visit(node, quals: Tuple[str, ...], cls: Optional[str],
              root_fn: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                class_names.add(child.name)
                visit(child, quals + (child.name,), child.name, root_fn)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = ".".join(quals + (child.name,))
                scope = _FnScope(qualname=qual, node=child,
                                 owning_class=cls,
                                 scope_root=root_fn or qual)
                # the annotation may sit on any line of the def header
                # (a multi-line signature puts the `:` past the def line)
                body_start = child.body[0].lineno if child.body \
                    else child.lineno + 1
                header = "\n".join(
                    lines[child.lineno - 1:
                          min(body_start - 1, len(lines))]
                    or [lines[child.lineno - 1]
                        if child.lineno <= len(lines) else ""])
                scope.requires = _REQUIRES_LOCK_RE.findall(header)
                scopes.append(scope)
                visit(child, quals + (child.name,), cls,
                      root_fn or qual)
            else:
                visit(child, quals, cls, root_fn)

    visit(tree, (), None, None)
    return scopes, class_names


def _guarded_fields(tree: ast.Module,
                    lines: List[str]) -> Dict[str, Dict[str, str]]:
    """class name -> {field: guarding lock path} from `# guarded-by:`
    comments on `self.<field> = ...` lines in `__init__`."""
    out: Dict[str, Dict[str, str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        init = next((f for f in node.body
                     if isinstance(f, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and f.name == "__init__"), None)
        if init is None:
            continue
        fields: Dict[str, str] = {}
        for st in ast.walk(init):
            targets: List[ast.expr] = []
            if isinstance(st, ast.Assign):
                targets = st.targets
            elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                targets = [st.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and st.lineno <= len(lines):
                    m = _GUARDED_BY_RE.search(lines[st.lineno - 1])
                    if m:
                        fields[t.attr] = m.group(1)
        if fields:
            out[node.name] = fields
    return out


def _module_spawns_threads(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d[-1] == "Thread" \
                    and (len(d) == 1 or d[-2] == "threading"):
                return True
    return False


def check_concurrency(module) -> List[Finding]:
    """All R6-R10 findings for one indexed module (astlint.ModuleIndex:
    needs .path, .tree, .lines)."""
    findings: List[Finding] = []
    tree, lines = module.tree, module.lines
    scopes, class_names = _collect_scopes(tree, lines)
    guarded = _guarded_fields(tree, lines)
    spawns = _module_spawns_threads(tree)

    def add(rule: str, node: ast.AST, symbol: str, message: str):
        line = getattr(node, "lineno", 1)
        text = lines[line - 1].strip() if 1 <= line <= len(lines) else ""
        findings.append(Finding(
            rule=rule, path=module.path, line=line,
            col=getattr(node, "col_offset", 0) + 1,
            symbol=symbol, message=message, line_text=text))

    # lock-order graph nodes/edges accumulated module-wide for R6
    edge_sites: Dict[Tuple[str, str], List[Tuple[str, _Held, _Held]]] = {}

    for scope in scopes:
        base: List[_Held] = []
        for req in scope.requires:
            parts = req.split(".")
            base.append(_Held(_lock_id(parts, scope, class_names), req,
                              scope.node, manual=False))
        w = _FnWalker(scope, class_names, base)
        w.walk()

        for outer, inner in w.edges:
            edge_sites.setdefault((outer.lock_id, inner.lock_id),
                                  []).append((scope.qualname, outer,
                                              inner))

        # ---- R7: guarded fields only touched under their lock
        fields = guarded.get(scope.owning_class or "", {})
        if fields and scope.node.name != "__init__":
            seen: Set[int] = set()
            for attr, held in w.accesses:
                if id(attr) in seen:
                    continue
                seen.add(id(attr))
                if not (isinstance(attr.value, ast.Name)
                        and attr.value.id == "self"):
                    continue
                lock_path = fields.get(attr.attr)
                if lock_path is None:
                    continue
                need = _lock_id(lock_path.split("."), scope, class_names)
                if need not in held:
                    add("R7", attr, scope.qualname,
                        f"`self.{attr.attr}` is guarded-by "
                        f"`{lock_path}` but is touched without holding "
                        "it (take the lock, or annotate the method "
                        f"`# requires-lock: {lock_path}` if every "
                        "caller holds it)")

        # ---- R8 / R9 / R10 over call sites
        for call, held_ids, held_texts in w.calls:
            name = _trailing_name(call.func)
            kwargs = {kw.arg for kw in call.keywords}

            if spawns and name in DISPATCH_NAMES \
                    and scope.node.name not in DISPATCH_NAMES:
                if not any(h.split(".")[-1].split(":")[-1]
                           == DISPATCH_LOCK_NAME for h in held_ids):
                    add("R8", call, scope.qualname,
                        f"multi-device dispatch `{name}()` in a "
                        "thread-spawning module outside `with "
                        "dispatch_lock:` — concurrent dispatch "
                        "interleaves per-device enqueue order across "
                        "threads and wedges the partition rendezvous "
                        "(the PR 18 deadlock; see parallel/dp.py)")

            if held_ids:
                held_str = ", ".join(held_texts)
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr == "acquire":
                    add("R9", call, scope.qualname,
                        f"nested `.acquire()` while holding "
                        f"[{held_str}] — blocking on a second lock "
                        "under a held one is the deadlock half of a "
                        "lock-order inversion; prefer nested `with` so "
                        "R6 can order-check it")
                elif name in _DEVICE_CALL_NAMES:
                    add("R9", call, scope.qualname,
                        f"`{name}()` (device call) while holding "
                        f"[{held_str}] — every other thread contending "
                        "for the lock stalls for the full device "
                        "round-trip")
                elif isinstance(call.func, ast.Attribute) \
                        and call.func.attr in _UNTIMED_BLOCKING_ATTRS \
                        and not call.args \
                        and not ({"timeout", "block"} & kwargs):
                    add("R9", call, scope.qualname,
                        f"untimed `.{call.func.attr}()` while holding "
                        f"[{held_str}] — if the wakeup source needs "
                        "this lock the program deadlocks; pass a "
                        "timeout or release first")

            d = _dotted(call.func)
            if d and d[-1] == "Thread" \
                    and (len(d) == 1 or d[-2] == "threading"):
                missing = [k for k in ("name", "daemon")
                           if k not in kwargs]
                if missing:
                    add("R10", call, scope.qualname,
                        "threading.Thread(...) without "
                        f"{'/'.join(missing)}= — watchdog stall "
                        "events and blackbox.json post-mortems "
                        "identify threads BY NAME (unnamed renders "
                        "as Thread-N), and a non-daemon worker "
                        "hangs interpreter exit after a crash")

    # ---- R6: cycles in the module lock-order graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edge_sites:
        if a != b:
            graph.setdefault(a, set()).add(b)
        else:
            # self-edge: lexical re-entry of a held lock
            for fn, outer, inner in edge_sites[(a, b)]:
                add("R6", inner.node, fn,
                    f"`{inner.text}` re-entered while already held — "
                    "self-deadlock for a plain Lock (only an RLock "
                    "survives this; if so, disable inline with that "
                    "reason)")

    cyclic_edges = _edges_on_cycles(graph)
    for (a, b) in sorted(cyclic_edges):
        sites = edge_sites[(a, b)]
        others = sorted({fn for fn, _, _ in edge_sites.get((b, a), [])})
        who = ", ".join(others) if others else "another function"
        for fn, outer, inner in sites:
            add("R6", inner.node, fn,
                f"lock-order cycle: takes `{outer.text}` then "
                f"`{inner.text}`, but {who} nests them in the "
                "opposite order — threads interleaving these "
                "functions deadlock; pick one global order")

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _edges_on_cycles(graph: Dict[str, Set[str]]) -> Set[Tuple[str, str]]:
    """Edges whose endpoints share a strongly connected component (every
    such edge participates in some cycle)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    comp: Dict[str, int] = {}
    counter = [0]
    ncomp = [0]

    def strongconnect(v: str):
        # iterative Tarjan (fixtures can be arbitrarily deep)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp[w] = ncomp[0]
                    if w == node:
                        break
                ncomp[0] += 1
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    nodes = set(graph) | {b for bs in graph.values() for b in bs}
    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return {(a, b) for a, bs in graph.items() for b in bs
            if comp.get(a) == comp.get(b)}
