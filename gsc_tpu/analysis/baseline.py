"""Suppression baseline for gsc-lint.

The linter over-approximates (name-based call resolution, no dataflow), so
accepted pre-existing cases — trace-time constants, intentional drain-phase
syncs — live in a JSON baseline that CI treats as the zero line: only NEW
unsuppressed findings fail the gate.  Every entry carries a mandatory
one-line ``reason`` so the suppression is reviewable, and matching is by
line-number-independent fingerprint (see findings.fingerprint) so pure
code motion never invalidates it.

Inline escape hatch: a source line containing ``gsc-lint: disable=R<k>``
(or ``disable=ALL``) suppresses findings of that rule on that line without
a baseline entry — for cases where the justification is best kept next to
the code.
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

from .findings import Finding, LintResult

BASELINE_VERSION = 1
_INLINE_RE = re.compile(r"gsc-lint:\s*disable=([A-Za-z0-9,]+)")


def inline_suppression(line_text: str, rule: str) -> bool:
    """True when ``line_text`` carries an inline disable for ``rule``."""
    m = _INLINE_RE.search(line_text)
    if not m:
        return False
    rules = {r.strip().upper() for r in m.group(1).split(",")}
    return "ALL" in rules or rule.upper() in rules


def load_baseline(path: Optional[str]) -> List[Dict]:
    """Baseline entries (empty when no file).  A present-but-corrupt
    baseline raises: silently linting against nothing would let regressions
    through while the gate reports green."""
    if not path or not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} in "
            f"{path} (expected {BASELINE_VERSION})")
    entries = doc.get("suppressions", [])
    for e in entries:
        if not e.get("fingerprint"):
            raise ValueError(f"baseline entry missing fingerprint: {e}")
        if not e.get("reason"):
            raise ValueError(
                f"baseline entry {e.get('fingerprint')} has no reason — "
                "every suppression must say why it is accepted")
    return entries


def save_baseline(path: str, findings: List[Finding],
                  existing: Optional[List[Dict]] = None,
                  preserve: Optional[List[Dict]] = None) -> int:
    """Write a baseline covering ``findings``; existing entries keep their
    hand-written reasons, new ones get a TODO reason to be filled in.
    ``preserve`` carries entries OUTSIDE the current run's scope (a
    ``--rules`` subset or a path subset) verbatim — a partial rewrite
    must not delete suppressions it never re-checked.  Returns the number
    of entries written."""
    by_fp = {e["fingerprint"]: e for e in (existing or [])}
    entries = []
    written = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        if f.fingerprint in written:
            # identical flagged lines in one function share a fingerprint
            # — one entry suppresses (and one reason covers) all of them
            continue
        written.add(f.fingerprint)
        prev = by_fp.get(f.fingerprint)
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "line_text": f.line_text,
            "reason": (prev or {}).get(
                "reason", "TODO: justify or fix this finding"),
        })
    seen = {e["fingerprint"] for e in entries}
    for e in sorted(preserve or [],
                    key=lambda e: (e.get("path", ""), e["fingerprint"])):
        if e["fingerprint"] not in seen:
            seen.add(e["fingerprint"])
            entries.append(e)
    doc = {"version": BASELINE_VERSION, "suppressions": entries}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return len(entries)


def apply_baseline(findings: List[Finding],
                   entries: List[Dict]) -> Tuple[List[Finding],
                                                 List[Finding],
                                                 List[Dict]]:
    """Partition raw findings into (unsuppressed, suppressed, stale
    baseline entries).  Inline ``gsc-lint: disable`` markers are honored
    first, then fingerprint matches."""
    by_fp = {e["fingerprint"]: e for e in entries}
    matched = set()
    live: List[Finding] = []
    quiet: List[Finding] = []
    for f in findings:
        if inline_suppression(f.line_text, f.rule):
            f.suppressed_by = "inline"
            quiet.append(f)
            continue
        entry = by_fp.get(f.fingerprint)
        if entry is not None:
            f.suppressed_by = entry["reason"]
            matched.add(f.fingerprint)
            quiet.append(f)
        else:
            live.append(f)
    stale = [e for fp, e in by_fp.items() if fp not in matched]
    return live, quiet, stale


def build_result(findings: List[Finding], entries: List[Dict],
                 files: int) -> LintResult:
    live, quiet, stale = apply_baseline(findings, entries)
    return LintResult(findings=live, suppressed=quiet, files=files,
                      stale_suppressions=stale)
