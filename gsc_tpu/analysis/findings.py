"""Finding records + stable fingerprints for gsc-lint.

A finding pins a rule violation to (file, function, source line).  The
fingerprint deliberately EXCLUDES the line number: refactors that shift
code up or down must not invalidate the suppression baseline, so identity
is the hash of (rule, relative path, enclosing qualname, normalized source
text of the offending line).  Two identical lines in the same function
share a fingerprint — suppressing one suppresses both, which is the
conservative direction for a baseline (documented in tools/gsc_lint.py).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# rule ids are stable API — the baseline file, README table and fixture
# tests all reference them
RULE_IDS = ("R1", "R2", "R3", "R4", "R5",
            "R6", "R7", "R8", "R9", "R10")

RULE_TITLES = {
    "R1": "host-sync call inside jit-traced code",
    "R2": "variable reused after being donated to a jitted call",
    "R3": "impure host state (clock/RNG/global) inside jit-traced code",
    "R4": "dot/einsum in a bf16-policy module without "
          "preferred_element_type",
    "R5": "bare Python scalar passed to a jitted entry point "
          "(weak-type retrace)",
    "R6": "lock-order cycle (same locks nested in opposite orders)",
    "R7": "guarded-by field touched outside a `with` on its lock",
    "R8": "multi-device dispatch in a thread-spawning module outside "
          "dispatch_lock (the PR 18 deadlock class)",
    "R9": "blocking call (untimed get/wait/join/result, nested "
          "acquire, device call) while holding a lock",
    "R10": "threading.Thread without name=/daemon= (unnamed threads "
           "break watchdog/blackbox post-mortems)",
}


def fingerprint(rule: str, path: str, symbol: str, line_text: str) -> str:
    """Line-number-independent identity of a finding (baseline key)."""
    norm = "".join(line_text.split())
    digest = hashlib.sha1(
        f"{rule}|{path}|{symbol}|{norm}".encode()).hexdigest()
    return digest[:16]


@dataclass
class Finding:
    rule: str                 # "R1".."R5"
    path: str                 # repo-relative posix path
    line: int                 # 1-based
    col: int
    symbol: str               # enclosing function qualname ("<module>" ok)
    message: str
    line_text: str = ""       # stripped source of the offending line
    suppressed_by: Optional[str] = None   # baseline reason / "inline"

    @property
    def fingerprint(self) -> str:
        return fingerprint(self.rule, self.path, self.symbol,
                           self.line_text)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.symbol}] {self.message}")

    def to_json(self) -> Dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "symbol": self.symbol,
            "message": self.message, "line_text": self.line_text,
            "fingerprint": self.fingerprint,
            "suppressed_by": self.suppressed_by,
        }


@dataclass
class LintResult:
    """Partitioned outcome of one lint run."""

    findings: List[Finding] = field(default_factory=list)    # unsuppressed
    suppressed: List[Finding] = field(default_factory=list)
    files: int = 0
    # baseline entries whose fingerprint matched nothing this run — stale
    # suppressions that should be pruned (reported, never fatal)
    stale_suppressions: List[Dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out
