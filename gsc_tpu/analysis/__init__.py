"""Static analysis + runtime sentinels for the jit discipline.

Three generations of hand-won invariants — donation safety (PR 1), the
telemetry contracts (PR 2), the precision-policy dtype discipline (PR 3)
— are enforced here mechanically:

- :mod:`~gsc_tpu.analysis.astlint` — the AST linter behind
  ``tools/gsc_lint.py`` (rules R1–R5: host syncs in traced code,
  use-after-donation, impure trace-time state, missing
  ``preferred_element_type`` in bf16-policy modules, weak-type scalar
  args at jitted entry points).
- :mod:`~gsc_tpu.analysis.concur` — the concurrency-discipline rules
  (R6–R10: lock-order cycles, ``# guarded-by:`` field discipline,
  multi-device dispatch outside ``dispatch_lock`` — the PR 18 deadlock
  class — blocking calls while holding a lock, and unnamed/non-daemon
  thread constructors), run through the same driver and baseline.
- :mod:`~gsc_tpu.analysis.baseline` — the suppression baseline that
  encodes accepted pre-existing cases (each with a written reason), so
  CI fails only on NEW findings.
- :mod:`~gsc_tpu.analysis.hlo` — compiled-HLO structure metrics:
  ``count_fusions`` (the op-count perf proxy that gates substep changes
  — the rejected bit-exact-but-281->294-fusions scatter-merge is the
  case it encodes), shared by ``tools/profile_substep.py``,
  ``tools/lever_sweep.py`` and the tier-1 fusion-budget test.
- :mod:`~gsc_tpu.analysis.sentinels` — the runtime side:
  :class:`CompileMonitor` (per-entry-point trace/compile counting, wired
  into ``events.jsonl`` as ``compile`` events), ``assert_no_retrace``
  and ``no_host_sync`` guards used by ``pytest -m analysis`` tests to
  prove the pipelined episode loop compiles once and performs zero
  unplanned device->host syncs in steady state.

The linter is stdlib-only (``ast``); jax is imported lazily by the
sentinels so ``tools/gsc_lint.py`` runs on a login node without device
init.
"""
from .astlint import DONATED_SIGS, lint_files, lint_paths
from .baseline import (apply_baseline, inline_suppression, load_baseline,
                       save_baseline)
from .concur import DISPATCH_NAMES, check_concurrency
from .findings import RULE_IDS, RULE_TITLES, Finding, LintResult
from .hlo import count_fusions, count_ops, hlo_text
from .sentinels import (DEFAULT_WATCH, CompileMonitor, HostSyncError,
                        RetraceError, assert_no_retrace, no_host_sync)

__all__ = [
    "DONATED_SIGS", "lint_files", "lint_paths",
    "DISPATCH_NAMES", "check_concurrency",
    "apply_baseline", "inline_suppression", "load_baseline",
    "save_baseline",
    "RULE_IDS", "RULE_TITLES", "Finding", "LintResult",
    "count_fusions", "count_ops", "hlo_text",
    "DEFAULT_WATCH", "CompileMonitor", "HostSyncError", "RetraceError",
    "assert_no_retrace", "no_host_sync",
]
