"""gsc-lint: repo-specific JAX static analysis (stdlib ``ast`` only).

Five rules encode the invariants three generations of perf PRs bought:

- **R1 host-sync-in-jit** — ``.item()``, ``float()``/``int()`` on
  non-literals, ``np.asarray``/``np.array``, ``block_until_ready``,
  ``jax.device_get`` inside functions reachable from jitted/scanned code.
  A host round-trip inside the fused ``episode_step`` path serializes the
  pipeline (Podracer's throughput argument, PAPERS.md).
- **R2 use-after-donation** — a variable passed in a donated argument
  position of a known donating entry point (``donated_jit`` table) and
  read again before being rebound: the PR 1 bug class (donated buffers
  are CONSUMED; XLA may have reused the memory).
- **R3 impure-in-jit** — ``time.time()``, Python/NumPy RNG, ``datetime``
  and ``global`` mutation inside traced code: baked in at trace time,
  silently frozen thereafter.
- **R4 accum-dtype** — dot/einsum/matmul in the bf16-policy modules
  (``ops/``, ``models/``) without ``preferred_element_type``: under the
  bf16 policy the MXU would accumulate in bf16 (the PR 3 contract is f32
  accumulation everywhere).  Calls lexically inside an f32-gated branch
  (``if <x>.dtype == jnp.float32:`` / ``if <dtype-ish> is None:`` bodies)
  are exempt — that is the repo's dtype-gate idiom for the verbatim
  legacy path.
- **R5 weak-scalar-arg** — numeric Python literals / scalar arithmetic
  passed positionally to a known jitted entry point: weak-typed scalars
  retrace on dtype flips (the trainer wraps with ``np.int32`` for this
  reason).  Known STATIC positions (``num_steps``, ``learn``) are exempt.

Tracing reachability is a deliberate over-approximation: jit roots are
functions decorated with jit/pmap/etc., functions passed to
``jax.jit``/``donated_jit``/``lax.scan``-family wrappers, and flax module
``__call__``/``setup`` bodies; edges resolve callees by bare name against
the project index (no type inference).  False positives land once in the
suppression baseline with a written reason (see baseline.py); false
negatives are bounded by the runtime sentinels (sentinels.py), which
check the same properties dynamically.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, LintResult
from .baseline import build_result, load_baseline

# ----------------------------------------------------------- configuration

# decorators / higher-order wrappers whose function arguments run traced
_WRAPPER_ATTRS = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "scan",
    "fori_loop", "while_loop", "cond", "switch", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "defvjp", "shard_map", "pallas_call",
    "associative_scan", "map",
}
# bare names accepted as wrappers without a jax/lax/nn prefix
_WRAPPER_NAMES = {"jit", "donated_jit", "vmap", "pmap", "shard_map"}
_WRAPPER_PREFIXES = {"jax", "lax", "nn", "pl", "pallas", "functools",
                     "partial", "flax"}

# donating entry points (donated_jit call sites in agents/ddpg.py and
# parallel/dp.py): method name -> (donated call-site positional indices
# with `self` already bound, donated parameter names, static positional
# indices exempt from R5).  The pjit-sharded dispatch path
# (ParallelDDPG._bind_sharded_dispatch) rebinds chunk_step /
# rollout_episodes / learn_burst with explicit in_/out_shardings but the
# SAME names, argument orders and donate_argnums as the donated_jit
# path, so the entries below cover both — and the PR 13 `tp` book only
# changes WHICH shardings those rebinds carry (resident-sharded state
# in place of replicated), never a name, order or donation, so no new
# row is needed for it either.  A new sharded entry point with a
# different signature must get its own row here.
DONATED_SIGS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...],
                              Tuple[int, ...]]] = {
    "episode_step": ((0, 1, 2), ("state", "buffer", "env_state"), (7, 8)),
    "rollout_episode": ((1, 2), ("buffer", "env_state"), (7,)),
    "learn_burst": ((0,), ("state",), (2,)),
    "chunk_step": ((0, 1), ("state", "buffers"), (7, 8)),
    "rollout_episodes": ((1,), ("buffers",), (7,)),
}

# which argument positions of a tracing wrapper are FUNCTIONS (passing a
# loop bound or carry by name must not mark that name as jit-traced);
# None = every positional arg (jit, vmap, grad, ... take only functions
# up front)
_WRAPPER_FN_ARGS: Dict[str, Optional[Tuple[int, ...]]] = {
    "scan": (0,), "fori_loop": (2,), "while_loop": (0, 1),
    "cond": (1, 2, 3), "switch": (1, 2, 3, 4, 5, 6, 7, 8),
    "associative_scan": (0,), "pallas_call": (0,), "donated_jit": (1,),
    "map": (0,),
}
_WRAPPER_FN_KWARGS = {"f", "fun", "body_fun", "cond_fun", "body", "kernel",
                      "true_fun", "false_fun", "method"}

# non-donating jitted entry points with STATIC positional args exempt from
# R5 (jit static_argnums by design take plain Python values)
STATIC_ARG_POSITIONS: Dict[str, Tuple[int, ...]] = {
    # DeviceTraffic.sample_batch: num_replicas is static_argnums=1 at
    # every jit site (tools/quality_anchor.py:220)
    "sample_batch": (1,),
}

_HOST_SYNC_METHOD_ATTRS = {"item"}   # zero-arg array methods

_NUMPY_NAMES = {"np", "numpy", "onp"}
_CAST_BUILTINS = {"float", "int", "bool"}
_DOT_ATTRS = {"einsum", "dot", "matmul", "dot_general", "tensordot"}
_TIME_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
               "perf_counter_ns", "time_ns"}
_DATETIME_ATTRS = {"now", "utcnow", "today"}


def _dotted(node: ast.AST) -> Optional[List[str]]:
    """['jax', 'lax', 'scan'] for jax.lax.scan; None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _trailing_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_wrapper_ref(node: ast.AST) -> bool:
    """Does this expression reference a tracing wrapper (jax.jit,
    donated_jit, lax.scan, ...)?"""
    d = _dotted(node)
    if not d:
        return False
    if len(d) == 1:
        return d[0] in _WRAPPER_NAMES
    return d[-1] in _WRAPPER_ATTRS and d[0] in _WRAPPER_PREFIXES


def _decorator_is_jit(dec: ast.AST) -> bool:
    """True for @jax.jit, @partial(jax.jit, ...), @donated-style wrappers."""
    for node in ast.walk(dec):
        if isinstance(node, (ast.Attribute, ast.Name)) \
                and _is_wrapper_ref(node):
            return True
    return False


# ------------------------------------------------------------------ index

@dataclass
class FunctionInfo:
    path: str                 # repo-relative posix path
    qualname: str
    name: str                 # bare name
    node: ast.AST             # FunctionDef / AsyncFunctionDef
    parent: Optional[str]     # enclosing function qualname (nested defs)
    is_root: bool = False
    callees: Set[str] = field(default_factory=set)   # bare callee names


@dataclass
class ModuleIndex:
    path: str
    tree: ast.Module
    lines: List[str]
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    # bare names referenced as function arguments of tracing wrappers
    jit_refs: Set[str] = field(default_factory=set)
    # class name -> is it (heuristically) a flax module
    flax_classes: Set[str] = field(default_factory=set)


def _collect_callees(fn_node: ast.AST) -> Set[str]:
    """Bare names of everything called in the body (nested defs skipped —
    they are indexed separately and linked via parent edges)."""
    out: Set[str] = set()
    for node in _walk_own(fn_node):
        if isinstance(node, ast.Call):
            # a tracing-wrapper call (jax.lax.scan(...)) is not an edge to
            # local functions that happen to be named scan/cond/map — its
            # FUNCTION arguments are collected into jit_refs instead
            if _is_wrapper_ref(node.func):
                continue
            name = _trailing_name(node.func)
            if name and not _is_at_indexed_update(node.func):
                out.add(name)
    return out


def _is_at_indexed_update(func: ast.AST) -> bool:
    """``x.at[idx].add(...)`` — jnp scatter methods, not call edges to
    project functions that happen to be named add/set/max/min."""
    return (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Subscript)
            and isinstance(func.value.value, ast.Attribute)
            and func.value.value.attr == "at")


def _walk_own(fn_node: ast.AST):
    """ast.walk over a function body EXCLUDING nested def/class subtrees
    (each nested def gets its own FunctionInfo).  Lambda bodies are
    INCLUDED: lambdas never get their own FunctionInfo, so a host sync
    inside ``lax.cond(p, lambda v: v.item(), ...)`` belongs to the
    enclosing function's scan."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def index_module(path: str, source: str) -> ModuleIndex:
    tree = ast.parse(source, filename=path)
    idx = ModuleIndex(path=path, tree=tree,
                      lines=source.splitlines())

    def visit(node, qual_stack: Tuple[str, ...], parent_fn: Optional[str],
              in_flax: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                bases = " ".join(
                    ".".join(_dotted(b) or ["?"]) for b in child.bases)
                is_flax = ("Module" in bases or "nn." in bases
                           or "linen" in bases or "struct" in bases)
                if is_flax:
                    idx.flax_classes.add(child.name)
                visit(child, qual_stack + (child.name,), parent_fn, is_flax)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = ".".join(qual_stack + (child.name,))
                info = FunctionInfo(
                    path=path, qualname=qual, name=child.name,
                    node=child, parent=parent_fn,
                    callees=_collect_callees(child))
                if any(_decorator_is_jit(d) for d in child.decorator_list):
                    info.is_root = True
                # flax module bodies always run under a trace
                if in_flax and child.name in ("__call__", "setup"):
                    info.is_root = True
                idx.functions[qual] = info
                visit(child, qual_stack + (child.name,), qual, in_flax)
            else:
                visit(child, qual_stack, parent_fn, in_flax)

    visit(tree, (), None, False)

    # function names handed to tracing wrappers anywhere in the module,
    # restricted to the wrapper's FUNCTION argument positions (a loop
    # bound passed to fori_loop by name is not a traced function)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_wrapper_ref(node.func):
            wrapper = _trailing_name(node.func)
            fn_pos = _WRAPPER_FN_ARGS.get(wrapper, None)
            for i, arg in enumerate(node.args):
                if fn_pos is not None and i not in fn_pos:
                    continue
                name = _trailing_name(arg)
                if name:
                    idx.jit_refs.add(name)
            for kw in node.keywords:
                if kw.arg in _WRAPPER_FN_KWARGS:
                    name = _trailing_name(kw.value)
                    if name:
                        idx.jit_refs.add(name)
    return idx


# ------------------------------------------------------------ reachability

def traced_functions(modules: Sequence[ModuleIndex]) -> Set[Tuple[str, str]]:
    """(path, qualname) of every function reachable from a jit root via
    bare-name call edges + nested-def parent edges."""
    by_name: Dict[str, List[FunctionInfo]] = {}
    all_fns: Dict[Tuple[str, str], FunctionInfo] = {}
    jit_refs: Set[str] = set()
    for m in modules:
        jit_refs |= m.jit_refs
        for info in m.functions.values():
            by_name.setdefault(info.name, []).append(info)
            all_fns[(m.path, info.qualname)] = info

    work: List[FunctionInfo] = []
    for info in all_fns.values():
        if info.is_root or info.name in jit_refs:
            work.append(info)
    traced: Set[Tuple[str, str]] = set()
    while work:
        info = work.pop()
        key = (info.path, info.qualname)
        if key in traced:
            continue
        traced.add(key)
        # call edges (bare-name resolution, project-wide)
        for callee in info.callees:
            for target in by_name.get(callee, ()):
                if (target.path, target.qualname) not in traced:
                    work.append(target)
        # nested defs inherit the parent's traced status
        prefix = info.qualname + "."
        for other in all_fns.values():
            if other.path == info.path \
                    and other.qualname.startswith(prefix) \
                    and (other.path, other.qualname) not in traced:
                work.append(other)
    return traced


# ------------------------------------------------------------------ rules

class _RuleContext:
    def __init__(self, module: ModuleIndex, info: FunctionInfo,
                 findings: List[Finding]):
        self.module = module
        self.info = info
        self.findings = findings

    def add(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 1)
        text = ""
        if 1 <= line <= len(self.module.lines):
            text = self.module.lines[line - 1].strip()
        self.findings.append(Finding(
            rule=rule, path=self.module.path, line=line,
            col=getattr(node, "col_offset", 0) + 1,
            symbol=self.info.qualname, message=message, line_text=text))


def _check_r1_r3(ctx: _RuleContext):
    """Host-sync (R1) and impurity (R3) checks over a traced body."""
    for node in _walk_own(ctx.info.node):
        if isinstance(node, ast.Global):
            ctx.add("R3", node,
                    "`global` mutation inside jit-traced code is baked in "
                    "at trace time")
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        d = _dotted(f)
        if isinstance(f, ast.Attribute):
            # block_until_ready has two spellings — the array method
            # x.block_until_ready() AND the module fn
            # jax.block_until_ready(tree); both are host syncs
            if f.attr == "block_until_ready":
                ctx.add("R1", node,
                        "block_until_ready forces a device->host sync "
                        "inside traced code")
            elif f.attr in _HOST_SYNC_METHOD_ATTRS and not node.args:
                ctx.add("R1", node,
                        f".{f.attr}() forces a device->host sync inside "
                        "traced code")
            elif f.attr in ("asarray", "array") and d \
                    and d[0] in _NUMPY_NAMES:
                ctx.add("R1", node,
                        f"{'.'.join(d)}() materializes a host array "
                        "inside traced code (use jnp)")
            elif f.attr == "device_get" and d and d[0] == "jax":
                ctx.add("R1", node,
                        "jax.device_get() syncs device->host inside "
                        "traced code")
            # R3: wall clocks, host RNG, datetime
            if d:
                if d[0] == "time" and f.attr in _TIME_ATTRS:
                    ctx.add("R3", node,
                            f"time.{f.attr}() reads the host clock at "
                            "trace time (frozen into the program)")
                elif len(d) >= 3 and d[0] in _NUMPY_NAMES \
                        and d[1] == "random":
                    ctx.add("R3", node,
                            f"{'.'.join(d)}() draws host RNG at trace "
                            "time (use jax.random with a threaded key)")
                elif d[0] == "random" and len(d) == 2:
                    ctx.add("R3", node,
                            f"random.{f.attr}() draws Python RNG at "
                            "trace time (use jax.random)")
                elif d[0] == "datetime" and f.attr in _DATETIME_ATTRS:
                    ctx.add("R3", node,
                            f"datetime.{f.attr}() reads the host clock "
                            "at trace time")
        elif isinstance(f, ast.Name):
            if f.id in _CAST_BUILTINS and node.args and not isinstance(
                    node.args[0], ast.Constant):
                ctx.add("R1", node,
                        f"{f.id}(...) on a non-literal forces a "
                        "device->host sync when the value is traced")


def _check_r2(ctx: _RuleContext):
    """Use-after-donation: linear scan with a twice-unrolled loop pass so
    a donation at the tail of an iteration is seen by the head of the
    next.  If/else branches are scanned sequentially on shared state (an
    over-approximation; exclusive-branch false positives go to the
    baseline)."""
    reported: Set[Tuple[int, str]] = set()

    def names_loaded(node: ast.AST, skip: Set[int]) -> List[ast.Name]:
        out = []
        for n in ast.walk(node):
            if id(n) in skip:
                continue
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.append(n)
        return out

    def bound_names(targets: Iterable[ast.AST]) -> Set[str]:
        out: Set[str] = set()
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and isinstance(
                        n.ctx, (ast.Store, ast.Del)):
                    out.add(n.id)
        return out

    def donations(st: ast.AST) -> List[Tuple[ast.Call, str, Set[str]]]:
        out = []
        for n in ast.walk(st):
            if not isinstance(n, ast.Call):
                continue
            callee = _trailing_name(n.func)
            sig = DONATED_SIGS.get(callee or "")
            if sig is None:
                continue
            positions, kw_names, _static = sig
            donated: Set[str] = set()
            for i, arg in enumerate(n.args):
                if i in positions and isinstance(arg, ast.Name):
                    donated.add(arg.id)
            for kw in n.keywords:
                if kw.arg in kw_names and isinstance(kw.value, ast.Name):
                    donated.add(kw.value.id)
            if donated:
                out.append((n, callee, donated))
        return out

    consumed: Dict[str, Tuple[str, int]] = {}

    def process(st: ast.AST):
        # 1) reads of consumed names (anywhere in the statement)
        for name in names_loaded(st, skip=set()):
            hit = consumed.get(name.id)
            if hit is not None:
                callee, dline = hit
                key = (name.lineno, name.id)
                if key not in reported:
                    reported.add(key)
                    ctx.add("R2", name,
                            f"`{name.id}` used after being donated to "
                            f"{callee}() at line {dline} — donated "
                            "buffers are consumed; rebind from the "
                            "call's return")
                consumed.pop(name.id, None)
        # 2) donation effects, then rebinding
        for call, callee, donated in donations(st):
            for nm in donated:
                consumed[nm] = (callee, call.lineno)
        for nm in _stmt_bound(st):
            consumed.pop(nm, None)

    def _stmt_bound(st: ast.AST) -> Set[str]:
        if isinstance(st, ast.Assign):
            return bound_names(st.targets)
        if isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            return bound_names([st.target])
        if isinstance(st, ast.Delete):
            return bound_names(st.targets)
        return set()

    def walk_body(stmts: Sequence[ast.AST]):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                process_expr_only(st.iter)
                for nm in bound_names([st.target]):
                    consumed.pop(nm, None)
                for _ in range(2):      # expose cross-iteration reuse
                    walk_body(st.body)
                walk_body(st.orelse)
            elif isinstance(st, ast.While):
                process_expr_only(st.test)
                for _ in range(2):
                    walk_body(st.body)
                walk_body(st.orelse)
            elif isinstance(st, ast.If):
                process_expr_only(st.test)
                walk_body(st.body)
                walk_body(st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    process_expr_only(item.context_expr)
                    if item.optional_vars is not None:
                        for nm in bound_names([item.optional_vars]):
                            consumed.pop(nm, None)
                walk_body(st.body)
            elif isinstance(st, ast.Try):
                walk_body(st.body)
                for h in st.handlers:
                    walk_body(h.body)
                walk_body(st.orelse)
                walk_body(st.finalbody)
            else:
                process(st)

    def process_expr_only(expr: ast.AST):
        if expr is not None:
            process(expr)

    walk_body(getattr(ctx.info.node, "body", []))


def _is_f32_gate(test: ast.AST) -> bool:
    """The repo's dtype-gate idiom: ``<x>.dtype == jnp.float32`` or
    ``<dtype-ish name> is None`` (compute_dtype / mdt / cd...)."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return False
    op = test.ops[0]
    if isinstance(op, ast.Eq):
        for side in (test.left, test.comparators[0]):
            d = _dotted(side)
            if d and d[-1] == "float32":
                return True
        return False
    if isinstance(op, ast.Is) and isinstance(test.comparators[0],
                                             ast.Constant) \
            and test.comparators[0].value is None:
        d = _dotted(test.left)
        if d:
            last = d[-1].lower()
            return "dt" in last or "dtype" in last
    return False


def _check_r4(ctx: _RuleContext):
    """Missing preferred_element_type on MXU contractions in bf16-policy
    modules, skipping f32-gated branches."""

    def scan(nodes: Iterable[ast.AST], f32_safe: bool):
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(node, ast.If):
                gate = _is_f32_gate(node.test)
                scan([node.test], f32_safe)
                scan(node.body, f32_safe or gate)
                scan(node.orelse, f32_safe)
                continue
            if not f32_safe:
                if isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.MatMult):
                    ctx.add("R4", node,
                            "`@` matmul without an f32-accumulating "
                            "wrapper in a bf16-policy module (use "
                            "lax.dot_general with preferred_element_type "
                            "or gate the f32 path)")
                elif isinstance(node, ast.Call):
                    name = _trailing_name(node.func)
                    d = _dotted(node.func)
                    jaxish = d and d[0] in ("jnp", "jax", "lax")
                    if name in _DOT_ATTRS and jaxish and not any(
                            kw.arg == "preferred_element_type"
                            for kw in node.keywords):
                        ctx.add("R4", node,
                                f"{name}() without preferred_element_"
                                "type in a bf16-policy module — the MXU "
                                "would accumulate in the operand dtype")
            scan(ast.iter_child_nodes(node), f32_safe)

    scan(getattr(ctx.info.node, "body", []), False)


def _check_r5(ctx: _RuleContext, entry_names: Set[str]):
    """Bare Python scalars at jitted-entry call sites."""
    for node in _walk_own(ctx.info.node):
        if not isinstance(node, ast.Call):
            continue
        callee = _trailing_name(node.func)
        if callee not in entry_names:
            continue
        static_pos: Tuple[int, ...] = STATIC_ARG_POSITIONS.get(callee, ())
        if callee in DONATED_SIGS:
            static_pos = DONATED_SIGS[callee][2]
        for i, arg in enumerate(node.args):
            if i in static_pos:
                continue
            bad = None
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, (int, float)) and not isinstance(
                        arg.value, bool):
                bad = f"literal {arg.value!r}"
            elif isinstance(arg, ast.UnaryOp) and isinstance(
                    arg.operand, ast.Constant):
                bad = "signed literal"
            elif isinstance(arg, ast.BinOp) and not isinstance(
                    arg.op, ast.MatMult):
                leaves = [n for n in ast.walk(arg)
                          if isinstance(n, (ast.Name, ast.Constant))]
                calls = [n for n in ast.walk(arg)
                         if isinstance(n, ast.Call)]
                if leaves and not calls:
                    bad = "scalar arithmetic"
            if bad:
                ctx.add("R5", arg,
                        f"{bad} passed positionally to jitted "
                        f"{callee}() — weak-typed scalars retrace on "
                        "dtype flips; wrap with np.int32/jnp.asarray "
                        "(static args are exempt via DONATED_SIGS)")


# ------------------------------------------------------------------ driver

def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d != "__pycache__" and not d.startswith(".")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _is_policy_module(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return "ops" in parts or "models" in parts


def lint_files(files: Sequence[str], rules: Optional[Set[str]] = None,
               root: Optional[str] = None) -> Tuple[List[Finding], int]:
    """Raw (un-baselined) findings over ``files``.  ``root`` anchors the
    repo-relative paths used in fingerprints (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    if not rules:
        from .findings import RULE_IDS
        rules = set(RULE_IDS)
    modules: List[ModuleIndex] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            rel = os.path.relpath(os.path.abspath(path), root)
            modules.append(index_module(rel.replace(os.sep, "/"), source))
        except (OSError, SyntaxError) as e:
            raise RuntimeError(f"gsc-lint cannot parse {path}: {e}") from e

    traced = traced_functions(modules)
    # R5 call-site entry points: jit-decorated names are global (methods
    # are called cross-module), but bare jit_refs stay module-local —
    # `jax.jit(call)` in one tool must not flag every `call()` elsewhere
    decorated_names = set(DONATED_SIGS)
    for m in modules:
        for info in m.functions.values():
            if info.is_root:
                decorated_names.add(info.name)

    findings: List[Finding] = []
    # lazy import: concur borrows nothing from this module at import
    # time, but keeping the edge one-directional avoids a cycle
    from .concur import check_concurrency
    concur_rules = {"R6", "R7", "R8", "R9", "R10"}
    for m in modules:
        policy_module = _is_policy_module(m.path)
        entry_names = decorated_names | m.jit_refs
        for info in m.functions.values():
            ctx = _RuleContext(m, info, findings)
            in_traced = (m.path, info.qualname) in traced
            if in_traced and ("R1" in rules or "R3" in rules):
                _check_r1_r3(ctx)
            if "R2" in rules:
                _check_r2(ctx)
            if "R4" in rules and policy_module:
                _check_r4(ctx)
            if "R5" in rules:
                _check_r5(ctx, entry_names)
        if rules & concur_rules:
            findings.extend(check_concurrency(m))
    # R1/R3 (and the concurrency family's shared walker) emit together,
    # so filter to the requested subset here
    findings = [f for f in findings if f.rule in rules]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, len(modules)


def lint_paths(paths: Sequence[str], baseline_path: Optional[str] = None,
               rules: Optional[Set[str]] = None,
               root: Optional[str] = None) -> LintResult:
    """Lint files/directories and apply the suppression baseline."""
    files = _iter_py_files(paths)
    raw, nfiles = lint_files(files, rules=rules, root=root)
    entries = load_baseline(baseline_path)
    if rules:
        entries = [e for e in entries
                   if e.get("rule") in rules or not e.get("rule")]
    return build_result(raw, entries, nfiles)
