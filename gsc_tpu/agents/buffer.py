"""On-device replay buffer — generic pytree ring buffer in HBM.

The reference's GraphReplayBuffer stores torch-geometric ``Data`` objects in
a numpy *object* array and re-batches them on every sample
(src/rlsp/agents/buffer.py:16-89) — host memory, pointer chasing, CPU
collation.  Here observations are already fixed-shape pytrees (GraphObs or
flat vectors), so the whole buffer is a pytree with a leading [capacity]
axis resident in device memory: ``add`` is a dynamic-index scatter, ``sample``
a gather — both jit/scan-able, so rollout and learning never leave the
device.  Works for any transition pytree (graph obs store nodes, edge_index,
masks per transition, which also preserves cross-topology replay when the
topology schedule swaps networks mid-training).
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class ReplayBuffer:
    """Ring buffer (reference: buffer.py:16-54 ring semantics)."""

    data: Any                # pytree, each leaf [capacity, ...]
    pos: jnp.ndarray         # [] i32 next write slot
    size: jnp.ndarray       # [] i32 valid entries


def buffer_init(example: Any, capacity: int) -> ReplayBuffer:
    """Allocate from an example transition pytree (shapes/dtypes copied)."""
    data = jax.tree_util.tree_map(
        lambda x: jnp.zeros((capacity,) + jnp.shape(x), jnp.asarray(x).dtype),
        example)
    return ReplayBuffer(data=data, pos=jnp.zeros((), jnp.int32),
                        size=jnp.zeros((), jnp.int32))


def buffer_add(buf: ReplayBuffer, item: Any) -> ReplayBuffer:
    """Insert one transition (buffer.py:33-54)."""
    capacity = jax.tree_util.tree_leaves(buf.data)[0].shape[0]
    data = jax.tree_util.tree_map(
        lambda d, x: jax.lax.dynamic_update_index_in_dim(
            d, jnp.asarray(x).astype(d.dtype), buf.pos, 0),
        buf.data, item)
    return ReplayBuffer(data=data, pos=(buf.pos + 1) % capacity,
                        size=jnp.minimum(buf.size + 1, capacity))


def buffer_sample(buf: ReplayBuffer, key, batch_size: int) -> Any:
    """Uniform sample of ``batch_size`` transitions (buffer.py:56-67)."""
    idx = jax.random.randint(key, (batch_size,), 0,
                             jnp.maximum(buf.size, 1))
    return jax.tree_util.tree_map(lambda d: d[idx], buf.data)
