"""On-device replay buffer — generic pytree ring buffer in HBM.

The reference's GraphReplayBuffer stores torch-geometric ``Data`` objects in
a numpy *object* array and re-batches them on every sample
(src/rlsp/agents/buffer.py:16-89) — host memory, pointer chasing, CPU
collation.  Here observations are already fixed-shape pytrees (GraphObs or
flat vectors), so the whole buffer is a pytree with a leading [capacity]
axis resident in device memory: ``add`` is a dynamic-index scatter, ``sample``
a gather — both jit/scan-able, so rollout and learning never leave the
device.  Works for any transition pytree (graph obs store nodes, edge_index,
masks per transition, which also preserves cross-topology replay when the
topology schedule swaps networks mid-training).

Storage layout: per-transition leaves with ndim >= 2 (e.g. GraphObs.nodes
[N, F], edge_index [2, E]) are stored FLATTENED to 1-D — [capacity, N*F] —
and restored to their original shapes on sampling.  Ragged trailing dims
like [24, 3] tile poorly on TPU and made XLA ping-pong the whole buffer
between layouts on every rollout step (two full-buffer copies per step,
~25% of the measured step wall at B=512); flat trailing dims keep one
layout end-to-end.  The original shapes ride on the buffer as static aux
data (``shapes``, aligned with ``tree_leaves(data)`` order; None for
leaves stored as-is).
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class ReplayBuffer:
    """Ring buffer (reference: buffer.py:16-54 ring semantics)."""

    data: Any                # pytree, each leaf [capacity, ...]
    pos: jnp.ndarray         # [] i32 next write slot
    size: jnp.ndarray       # [] i32 valid entries
    # per-leaf original trailing shape for flattened (ndim>=2) leaves,
    # aligned with tree_leaves(data); None = leaf stored unflattened
    shapes: Tuple = struct.field(pytree_node=False, default=None)


def transition_shapes(example: Any) -> Tuple:
    """Static per-leaf storage spec from an example transition."""
    return tuple(
        tuple(jnp.shape(x)) if jnp.ndim(x) >= 2 else None
        for x in jax.tree_util.tree_leaves(example))


def flatten_transition(item: Any) -> Any:
    """Flatten ndim>=2 leaves of one transition to 1-D (storage form)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x).reshape(-1) if jnp.ndim(x) >= 2
        else jnp.asarray(x), item)


def restore_batch(shapes: Tuple, batch: Any, lead: int = 1) -> Any:
    """Reshape a sampled batch's flattened leaves back to their original
    per-transition shapes (``lead`` = number of leading batch axes).
    ``shapes=None`` (a buffer built without the storage spec) means nothing
    was flattened — return the batch as-is."""
    if shapes is None:
        return batch
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    out = [l if s is None else l.reshape(l.shape[:lead] + s)
           for l, s in zip(leaves, shapes)]
    return jax.tree_util.tree_unflatten(treedef, out)


def buffer_init(example: Any, capacity: int) -> ReplayBuffer:
    """Allocate from an example transition pytree (shapes/dtypes copied)."""
    flat = flatten_transition(example)
    data = jax.tree_util.tree_map(
        lambda x: jnp.zeros((capacity,) + jnp.shape(x), jnp.asarray(x).dtype),
        flat)
    return ReplayBuffer(data=data, pos=jnp.zeros((), jnp.int32),
                        size=jnp.zeros((), jnp.int32),
                        shapes=transition_shapes(example))


def buffer_add(buf: ReplayBuffer, item: Any) -> ReplayBuffer:
    """Insert one transition (buffer.py:33-54)."""
    capacity = jax.tree_util.tree_leaves(buf.data)[0].shape[0]
    data = jax.tree_util.tree_map(
        lambda d, x: jax.lax.dynamic_update_index_in_dim(
            d, jnp.asarray(x).astype(d.dtype), buf.pos, 0),
        buf.data, flatten_transition(item))
    return ReplayBuffer(data=data, pos=(buf.pos + 1) % capacity,
                        size=jnp.minimum(buf.size + 1, capacity),
                        shapes=buf.shapes)


def buffer_nbytes(buf: ReplayBuffer, local: bool = False) -> int:
    """Total replay storage footprint in bytes.  The buffer is the largest
    HBM resident of a training run; the pipeline telemetry logs this so the
    copy traffic that ``donate_argnums`` eliminates (one full-buffer copy
    per episode on the non-donating path) is attributable.

    Summed per leaf from the ACTUAL storage dtype (``l.dtype.itemsize``),
    never from an assumed element size — under a mixed-dtype policy
    (bf16 obs/action leaves next to f32 reward/done, PrecisionPolicy.
    replay_dtype) the ``replay bytes`` gauge must reflect the halved
    residency, not double-count bf16 leaves as f32
    (tests/test_precision.py::test_buffer_nbytes_mixed_dtypes).

    ``local=True`` reports the bytes RESIDENT ON THIS PROCESS'S devices
    when the ring is dp-sharded under a mesh plan: ``l.size`` on a jax
    Array is the GLOBAL element count, so the default accounting
    overstates a sharded ring's per-host residency by the dp factor —
    local sums each leaf's addressable shards instead (identical to the
    global number for host numpy leaves and unsharded device arrays)."""
    total = 0
    for l in jax.tree_util.tree_leaves(buf.data):
        shards = getattr(l, "addressable_shards", None) if local else None
        if shards is not None:
            total += sum(s.data.size * s.data.dtype.itemsize
                         for s in shards)
        else:
            total += l.size * l.dtype.itemsize
    return total


def buffer_fill_frac(buf: ReplayBuffer) -> float:
    """Global fill fraction of the ring: valid entries over capacity,
    summed across every replica row when ``size`` is batched [B] (the
    parallel ring) and correct when ``size``/``data`` live sharded under
    a plan — ``jnp.sum`` reduces over the GLOBAL array, so per-shard
    fills never masquerade as the whole ring's (the async replay-fill
    gauge; scalar rings divide by their scalar capacity)."""
    import numpy as np

    capacity = jax.tree_util.tree_leaves(buf.data)[0].shape[
        1 if jnp.ndim(buf.size) >= 1 else 0]
    rows = max(1, int(np.prod(jnp.shape(buf.size)) or 1))
    denom = rows * int(capacity)
    return float(jnp.sum(buf.size)) / denom if denom else 0.0


def buffer_sample(buf: ReplayBuffer, key, batch_size: int) -> Any:
    """Uniform sample of ``batch_size`` transitions (buffer.py:56-67),
    restored to original per-transition shapes."""
    idx = jax.random.randint(key, (batch_size,), 0,
                             jnp.maximum(buf.size, 1))
    raw = jax.tree_util.tree_map(lambda d: d[idx], buf.data)
    return restore_batch(buf.shapes, raw)
