"""DDPG learner — jitted rollout + learn-burst (reference:
src/rlsp/agents/simple_ddpg.py:101-329).

CleanRL-style DDPG: one actor, one critic, Polyak-averaged targets, Adam.
The reference steps the env and nets one Python call at a time on CPU; here
a whole episode's rollout is one ``lax.scan`` (actions, env physics, replay
writes all on device) and the end-of-episode learning burst is one
``lax.fori_loop`` of ``episode_steps`` gradient steps (simple_ddpg.py:307-325).
The pipelined trainer fuses both into ONE device call per episode
(``episode_step``); the two-call path (``rollout_episode`` + ``learn_burst``)
remains for chunked/serial drivers and is bit-identical.

Faithful semantics:
- warmup (< nb_steps_warmup_critic global steps): uniform random action
  masked to valid entries (simple_ddpg.py:184-187)
- after warmup: actor output scaled to [-1,1], Gaussian noise
  N(rand_mu, rand_sigma) added, unscaled back and clipped to [0,1]
  (simple_ddpg.py:188-201; the reference's `.clip(-1,1)` on the scaled
  action is a no-op it discards — not reproduced)
- post-processing threshold+renormalize before the env sees the action
  (simple_ddpg.py:248-249)
- critic target: r + gamma * (1 - done) * Q_target(s', clamp(pi_target(s'), -1, 1))
  (simple_ddpg.py:207-214)
- actor loss: -Q(s, pi(s)).mean() (simple_ddpg.py:221-227)
- Polyak tau = target_model_update each gradient step (simple_ddpg.py:229-234)
- train once per episode end: episode_steps gradient steps on batches of
  batch_size (simple_ddpg.py:300-325)

Precision (AgentConfig.precision -> PrecisionPolicy): learner state —
params, Polyak targets, Adam moments, PRNG — is ALWAYS f32 master state;
the bf16 policy only changes the networks' internal compute dtype (casts
live inside actor/critic apply) and the replay STORAGE dtype of obs/action
leaves (``example_transition``; ``buffer_add``'s write-side ``astype``
then rounds rollout transitions once on insert).  Rewards, done flags,
exploration noise, TD targets and the soft-update arithmetic never leave
f32, so the reward scale and tau=1e-4 target updates are unaffected.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..config.schema import AgentConfig
from ..env.env import ServiceCoordEnv
from ..models.nets import Actor, QNetwork, scale_action, unscale_action
from ..obs.learning import (accumulate_signal, learn_signal, replay_stats,
                            zero_learn_signal)
from ..resilience.guard import all_finite
from .buffer import ReplayBuffer, buffer_add, buffer_init, buffer_sample


@struct.dataclass
class DDPGState:
    """Learner state (networks, targets, optimizers, PRNG)."""

    actor_params: Any
    critic_params: Any
    target_actor_params: Any
    target_critic_params: Any
    actor_opt: Any
    critic_opt: Any
    rng: jnp.ndarray


def donated_jit(bound_self, method, static_argnums, donate_argnums):
    """Per-instance re-jit of a jitted method with buffer donation (the
    ParallelDDPG ``donate=True`` pattern, shared by both agent paths).
    Callers must treat the donated arguments as CONSUMED — always rebind
    from the return; comparison-style double-calls on the same inputs must
    construct the agent with the non-donating default."""
    fn = getattr(method, "__wrapped__", method)
    return partial(jax.jit(fn, static_argnums=static_argnums,
                           donate_argnums=donate_argnums), bound_self)


class DDPG:
    """Factory closing over static config; all methods are pure and jitted.

    ``donate=True`` aliases the large carried pytrees into their device
    calls so XLA updates them in place instead of copying every episode:
    the replay buffer (the largest HBM resident) and env-state carry are
    donated into the rollout, and the learner state into the learn burst /
    fused episode step.  ``obs`` is never donated (its leaves can alias
    env-state or topology buffers — double donation, which XLA rejects).
    """

    def __init__(self, env: ServiceCoordEnv, agent: AgentConfig,
                 gnn_impl: str = None, donate: bool = False,
                 learn_ledger=None):
        self.env = env
        self.agent = agent
        self.donate = donate
        # on-device learning-signal ledger (obs.learning.LearnLedgerSpec,
        # static — it rides on `self`): with a spec, the learn burst and
        # rollout fold per-topology |TD-error| segments, Q distribution
        # moments, per-layer param/grad norms and replay fill stats into
        # their EXISTING outputs (drained with the deferred drain, zero
        # new host syncs).  None (the default) traces the historic
        # programs byte for byte — the no-ledger path is the pre-ledger
        # stack.
        self.learn_ledger = learn_ledger
        self.action_dim = env.limits.action_dim
        gnn_impl = gnn_impl or agent.gnn_impl  # config-selected embedder
        sched_shape = env.limits.scheduling_shape
        self.actor = Actor(agent=agent, action_dim=self.action_dim,
                           gnn_impl=gnn_impl, sched_shape=sched_shape)
        self.critic = QNetwork(agent=agent, gnn_impl=gnn_impl,
                               action_dim=self.action_dim,
                               sched_shape=sched_shape)
        self.opt = optax.adam(agent.learning_rate)
        if donate:
            cls = type(self)
            self.rollout_episode = donated_jit(
                self, cls.rollout_episode, static_argnums=(0, 8),
                donate_argnums=(2, 3))
            self.learn_burst = donated_jit(
                self, cls.learn_burst, static_argnums=(0, 3),
                donate_argnums=(1,))
            self.episode_step = donated_jit(
                self, cls.episode_step, static_argnums=(0, 8, 9),
                donate_argnums=(1, 2, 3))

    # ---------------------------------------------------------------- init
    def init(self, rng, sample_obs) -> DDPGState:
        k1, k2, k3 = jax.random.split(rng, 3)
        actor_params = self.actor.init(k1, sample_obs)
        critic_params = self.critic.init(
            k2, sample_obs, jnp.zeros(self.action_dim))
        # fresh init shares the target trees' device buffers with the online
        # trees; under donation that is a double donation of the same buffer
        # (XLA rejects it), so break the aliasing with a one-time copy
        copy = (jax.tree_util.tree_map(jnp.copy, (actor_params,
                                                  critic_params))
                if self.donate else (actor_params, critic_params))
        return DDPGState(
            actor_params=actor_params,
            critic_params=critic_params,
            target_actor_params=copy[0],
            target_critic_params=copy[1],
            actor_opt=self.opt.init(actor_params),
            critic_opt=self.opt.init(critic_params),
            rng=k3,
        )

    def example_transition(self, sample_obs):
        """Shape/dtype template of one replay transition.  Under a
        low-precision replay policy the float leaves of obs/next_obs and
        the action are stored in ``PrecisionPolicy.replay_dtype`` (halving
        the largest HBM resident); reward and done stay f32 so TD-target
        scale survives replay round-trips."""
        rd = self.agent.precision_policy.replay_cast_dtype
        obs, action = sample_obs, jnp.zeros(self.action_dim)
        if rd is not None:
            d = jnp.dtype(rd)
            obs = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x).astype(d)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
                sample_obs)
            action = action.astype(d)
        return {
            "obs": obs,
            "next_obs": obs,
            "action": action,
            "reward": jnp.zeros(()),
            "done": jnp.zeros(()),
            # which network the transition was collected on (the
            # Topology's topo_id: schedule position, or mix-entry index
            # in mixed-topology batches) — 4 bytes/transition, lets
            # replay analysis attribute cross-topology experience
            "topo_idx": jnp.zeros((), jnp.int32),
        }

    def init_buffer(self, sample_obs) -> ReplayBuffer:
        return buffer_init(self.example_transition(sample_obs),
                           self.agent.mem_limit)

    # ------------------------------------------------------------- actions
    def greedy_action(self, actor_params, obs):
        """The greedy inference policy as a pure, loweable function of
        (actor_params, obs): actor forward pass, clip to [0, 1], agent-side
        post-processing (threshold + renormalize) — exactly the per-step op
        sequence of ``Trainer.evaluate`` (inference.py:17-40 semantics: no
        noise, no warmup branch, no learning).

        Deliberately NOT jit-decorated: ``Trainer.evaluate`` runs it eagerly
        (identical op-by-op to the historical inline code), while the
        serving stack (``gsc_tpu.serve``) vmaps it over request batches and
        AOT-lowers/exports the result per batch bucket."""
        a = self.actor.apply(actor_params, obs)
        a = jnp.clip(a, 0.0, 1.0)
        return self.env.process_action(a)

    def choose_action(self, actor_params, obs, mask, global_step, key):
        """Warmup random masked action, else actor + Gaussian noise in scaled
        space (simple_ddpg.py:182-201)."""
        k1, k2 = jax.random.split(key)
        random_action = jax.random.uniform(k1, (self.action_dim,)) * mask

        def policy_action():
            a = self.actor.apply(actor_params, obs)
            scaled = scale_action(a)
            noise = self.agent.rand_mu + self.agent.rand_sigma * \
                jax.random.normal(k2, (self.action_dim,))
            return jnp.clip(unscale_action(scaled + noise), 0.0, 1.0)

        warmup = global_step < self.agent.nb_steps_warmup_critic
        return jax.lax.cond(warmup, lambda: random_action, policy_action)

    # ------------------------------------------------------------- rollout
    def _rollout_body(self, state: DDPGState, buffer: ReplayBuffer,
                      env_state, obs, topo, traffic,
                      episode_start_step: jnp.ndarray,
                      num_steps: int = None
                      ) -> Tuple["DDPGState", ReplayBuffer, Any, Any,
                                 Dict[str, jnp.ndarray]]:
        """Rollout scan shared by ``rollout_episode`` and the fused
        ``episode_step`` (traced inside their jits, never called raw)."""
        from ..env.actions import action_mask
        from ..env.permutation import ShuffleOps
        mask = action_mask(topo.node_mask, self.env.limits.num_sfcs,
                           self.env.limits.max_sfs)
        rng, sub = jax.random.split(state.rng)
        shuffle = ShuffleOps(self.agent, self.env.limits)
        sub, k0 = jax.random.split(sub)
        perm0 = shuffle.init_perm(k0)
        # obs in the carry lives in the current permuted frame; the env gets
        # actions mapped back through the inverse (gym_env.py:193-206 flow)
        obs = shuffle.permute_obs(obs, perm0)

        def step_fn(carry, i):
            env_state, obs, perm, buffer = carry
            k = jax.random.fold_in(sub, i)
            step_mask = shuffle.step_mask(obs, mask, perm)
            action = self.choose_action(state.actor_params, obs, step_mask,
                                        episode_start_step + i, k)
            action = self.env.process_action(action)
            env_state, next_obs, reward, done, info = self.env.step(
                env_state, topo, traffic, shuffle.env_action(action, perm))
            next_obs, next_perm = shuffle.advance(
                jax.random.fold_in(k, 1), next_obs, perm)
            buffer = buffer_add(buffer, {
                "obs": obs, "next_obs": next_obs, "action": action,
                "reward": reward, "done": done.astype(jnp.float32),
                "topo_idx": topo.topo_id,
            })
            stats = {"reward": reward, "succ_ratio": info["succ_ratio"],
                     "avg_e2e_delay": info["avg_e2e_delay"]}
            return (env_state, next_obs, next_perm, buffer), stats

        T = self.agent.episode_steps if num_steps is None else num_steps
        (env_state, obs, _, buffer), stats = jax.lax.scan(
            step_fn, (env_state, obs, perm0, buffer), jnp.arange(T))
        episode_stats = {
            "episodic_return": stats["reward"].sum(),
            "mean_succ_ratio": stats["succ_ratio"].mean(),
            "mean_e2e_delay": stats["avg_e2e_delay"].mean(),
            "final_succ_ratio": stats["succ_ratio"][-1],
            # divergence guardrail (resilience.guard): all-finite flag over
            # the learner state ENTERING this episode, computed on device
            # and drained with the deferred metrics — catches a poisoned
            # state even during warmup, when no learn burst runs (the
            # post-update flag lives in the learn metrics)
            "state_finite": all_finite(state),
        }
        if self.learn_ledger is not None:
            # replay fill/age computed ON DEVICE from the post-rollout
            # buffer (reading buffer.size host-side would sync the
            # dispatch head); drained with the other deferred stats
            episode_stats["replay"] = replay_stats(buffer)
        return state.replace(rng=rng), buffer, env_state, obs, episode_stats

    @partial(jax.jit, static_argnums=(0, 8))
    def rollout_episode(self, state: DDPGState, buffer: ReplayBuffer,
                        env_state, obs, topo, traffic,
                        episode_start_step: jnp.ndarray,
                        num_steps: int = None
                        ) -> Tuple["DDPGState", ReplayBuffer, Any, Any,
                                   Dict[str, jnp.ndarray]]:
        """One full episode as a lax.scan: action -> env.step -> buffer.add.
        Returns (state w/ fresh rng, buffer, final_env_state, final_obs,
        episode stats).  ``num_steps`` (static) overrides the scan length so
        an episode can run as several shorter device calls (see
        ParallelDDPG.rollout_episodes for the chunking contract)."""
        return self._rollout_body(state, buffer, env_state, obs, topo,
                                  traffic, episode_start_step, num_steps)

    @partial(jax.jit, static_argnums=(0, 8, 9))
    def episode_step(self, state: DDPGState, buffer: ReplayBuffer,
                     env_state, obs, topo, traffic,
                     episode_start_step: jnp.ndarray,
                     num_steps: int = None, learn: bool = False
                     ) -> Tuple["DDPGState", ReplayBuffer, Any, Any,
                                Dict[str, jnp.ndarray],
                                Dict[str, jnp.ndarray]]:
        """Fused rollout + learn: one device program per episode.

        Runs the chunked rollout scan and — when ``learn`` (static; the
        host decides it from the warmup schedule, which depends only on the
        episode index) — the end-of-episode learn burst in the SAME jitted
        call, eliminating the host round-trip between the two dispatches
        and letting XLA overlap the tail of the scan with the first
        gradient steps.  Returns (state, buffer, env_state, obs, stats,
        learn_metrics) with ``learn_metrics=None`` during warmup.  The op
        sequence is identical to ``rollout_episode`` followed by
        ``learn_burst``, so results are bit-identical to the two-call
        path."""
        state, buffer, env_state, obs, stats = self._rollout_body(
            state, buffer, env_state, obs, topo, traffic,
            episode_start_step, num_steps)
        metrics = None
        if learn:
            state, metrics = self._learn_burst(
                state,
                lambda k: buffer_sample(buffer, k, self.agent.batch_size))
        return state, buffer, env_state, obs, stats, metrics

    # ------------------------------------------------------------ learning
    def _critic_loss(self, critic_params, state: DDPGState, batch):
        next_a = jnp.clip(
            self.actor.apply(state.target_actor_params, batch["next_obs"]),
            -1.0, 1.0)  # clamp(-1,1), simple_ddpg.py:208
        q_next = self.critic.apply(state.target_critic_params,
                                   batch["next_obs"], next_a)[..., 0]
        target = batch["reward"] + (1.0 - batch["done"]) * self.agent.gamma * q_next
        q = self.critic.apply(critic_params, batch["obs"], batch["action"])[..., 0]
        # the residual IS the loss argument — naming it changes no op.
        # With the learn ledger the aux also carries it, so the burst can
        # segment |TD| per topology without recomputing the targets;
        # without a ledger the aux stays the historic single-tensor `q`.
        td = q - jax.lax.stop_gradient(target)
        aux = (q, td) if self.learn_ledger is not None else q
        return jnp.mean(td ** 2), aux

    def _actor_loss(self, actor_params, critic_params, batch):
        a = self.actor.apply(actor_params, batch["obs"])
        return -jnp.mean(self.critic.apply(critic_params, batch["obs"], a))

    def gradient_step(self, state: DDPGState, buffer: ReplayBuffer, key
                      ) -> Tuple[DDPGState, Dict[str, jnp.ndarray]]:
        """One (critic, actor, Polyak) update on a sampled batch
        (simple_ddpg.py:204-234, 307-325)."""
        batch = buffer_sample(buffer, key, self.agent.batch_size)
        return self.gradient_step_on_batch(state, batch)

    def gradient_step_on_batch(self, state: DDPGState, batch
                               ) -> Tuple[DDPGState, Dict[str, jnp.ndarray]]:
        (critic_loss, aux), cgrad = jax.value_and_grad(
            self._critic_loss, has_aux=True)(state.critic_params, state, batch)
        q_vals, td = aux if self.learn_ledger is not None else (aux, None)
        cupd, critic_opt = self.opt.update(cgrad, state.critic_opt)
        critic_params = optax.apply_updates(state.critic_params, cupd)

        actor_loss, agrad = jax.value_and_grad(self._actor_loss)(
            state.actor_params, critic_params, batch)
        aupd, actor_opt = self.opt.update(agrad, state.actor_opt)
        actor_params = optax.apply_updates(state.actor_params, aupd)

        tau = self.agent.target_model_update
        polyak = lambda t, p: jax.tree_util.tree_map(
            lambda tl, pl: tau * pl + (1 - tau) * tl, t, p)
        state = DDPGState(
            actor_params=actor_params, critic_params=critic_params,
            target_actor_params=polyak(state.target_actor_params, actor_params),
            target_critic_params=polyak(state.target_critic_params,
                                        critic_params),
            actor_opt=actor_opt, critic_opt=critic_opt, rng=state.rng)
        # grad norms ride along for run telemetry (events.jsonl) — computed
        # from the already-materialized grads, so the update path is
        # untouched and pipeline/serial bit-identity holds
        metrics = {"critic_loss": critic_loss, "actor_loss": actor_loss,
                   "q_values": q_vals.mean(),
                   "critic_grad_norm": optax.global_norm(cgrad),
                   "actor_grad_norm": optax.global_norm(agrad)}
        if self.learn_ledger is not None:
            # learning-signal ledger (obs.learning): consumes tensors the
            # update already materialized (td, grads, post-update params),
            # so the update math is untouched either way
            metrics["learn_signal"] = learn_signal(
                self.learn_ledger, batch["topo_idx"], td, q_vals,
                params={"actor": actor_params, "critic": critic_params},
                grads={"actor": agrad, "critic": cgrad})
        return state, metrics

    def _learn_burst(self, state: DDPGState, sample_fn, constrain=None,
                     steps: Optional[int] = None
                     ) -> Tuple[DDPGState, Dict[str, jnp.ndarray]]:
        """End-of-episode training: episode_steps gradient steps
        (simple_ddpg.py:307-325) as one fori_loop.  ``sample_fn(key)``
        yields a batch — single-buffer and cross-replica samplers both
        plug in here.

        ``steps`` overrides the per-burst gradient-step count (static —
        each distinct value is its own trace).  The async learner runs
        bursts against an EXTERNALLY-advancing replay (actors keep
        ingesting between bursts), where burst length is a pacing knob
        decoupled from the episode length the sync default encodes.

        ``constrain`` (optional; the sharded multi-chip path) re-pins the
        carried learner state — top of every gradient step AND the
        back-edge — to the layout the caller's plan intends.  The
        replicated/sharded books pin to REPLICATED: without it, GSPMD's
        fixpoint solve pulls the caller's sharded state layout INTO the
        loop carry and steps 2..N compute tensor-parallel with
        carving-dependent reduction order, breaking their bit-equality
        contract.  The ``tp`` book pins to its OWN sharded layout: there
        tensor-parallel compute is the point, and the constraint keeps
        the fixpoint ON that layout so every step's contractions psum
        the same way (acceptance is banded, see
        ``parallel.partition.tp_rules``).  ``None`` (the default, every
        single-agent path) traces the historic body verbatim."""
        rng, sub = jax.random.split(state.rng)
        state = state.replace(rng=sub)

        def body(i, carry):
            st, acc = carry
            if constrain is not None:
                st = constrain(st)
            batch = sample_fn(jax.random.fold_in(sub, i))
            st, metrics = self.gradient_step_on_batch(st, batch)
            if self.learn_ledger is not None:
                # TD segments ACCUMULATE across the burst (per-topology
                # learning pressure over all sampled batches); moments
                # and norms keep the last step's values — the same
                # last-write carry semantics as the loss metrics
                metrics = {**metrics, "learn_signal": accumulate_signal(
                    acc["learn_signal"], metrics["learn_signal"])}
            if constrain is not None:
                # pin the RETURNED carry too: the constraint on entry
                # alone leaves the loop's back-edge free for GSPMD to
                # settle on whatever layout minimizes the first step,
                # which then back-propagates through the Adam/Polyak
                # updates into the gradient dots — the update math must
                # stay on the INTENDED layout end to end (replicated for
                # the bit-exact books, the plan's sharded layout for tp)
                st = constrain(st)
            return st, metrics

        zero = {"critic_loss": jnp.zeros(()), "actor_loss": jnp.zeros(()),
                "q_values": jnp.zeros(()),
                "critic_grad_norm": jnp.zeros(()),
                "actor_grad_norm": jnp.zeros(())}
        if self.learn_ledger is not None:
            zero["learn_signal"] = zero_learn_signal(self.learn_ledger,
                                                     state)
        # `steps` is a STATIC jit arg (dp.py marks it static_argnums) —
        # int() here normalizes a Python int, never syncs a tracer
        n_steps = (int(steps) if steps is not None  # gsc-lint: disable=R1
                   else self.agent.learn_steps
                   if self.agent.learn_steps is not None
                   else self.agent.episode_steps)
        state, metrics = jax.lax.fori_loop(0, n_steps, body, (state, zero))
        # divergence guardrail: flag the POST-update learner state in the
        # same device program (no extra host sync — the trainer reads it
        # from the deferred metric drain and rolls back on violation)
        metrics = {**metrics, "state_finite": all_finite(state)}
        return state.replace(rng=rng), metrics

    @partial(jax.jit, static_argnums=(0, 3))
    def learn_burst(self, state: DDPGState, buffer: ReplayBuffer,
                    steps: Optional[int] = None
                    ) -> Tuple[DDPGState, Dict[str, jnp.ndarray]]:
        return self._learn_burst(
            state, lambda k: buffer_sample(buffer, k, self.agent.batch_size),
            steps=steps)
