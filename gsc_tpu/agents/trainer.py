"""Training driver — the host loop around the jitted rollout/learn kernels.

The analogue of SimpleDDPG.train + the experiment plumbing of
src/rlsp/agents/main.py: per episode it picks the scheduled topology,
samples traffic (host), dispatches the episode's device work, and logs
episode metrics (rewards.csv like result_writer.py:6-38, optional
TensorBoard like simple_ddpg.py:165-174).

The default ``pipeline=True`` path keeps the accelerator saturated between
episodes (Podracer-style, arXiv:2104.06272): a background thread PREFETCHES
episode k+1's topology/traffic (staged to device) while episode k runs, the
rollout scan and learn burst run as ONE fused jitted ``episode_step`` (no
host round-trip between them), per-episode metric syncs are DEFERRED one
episode so ``np.asarray`` never gates the next dispatch, and the replay
buffer / env-state carries are donated (updated in place in HBM instead of
copied every episode).  Results are bit-identical to the serial path —
per-episode PRNG streams are ``fold_in``-keyed by the episode index, so
look-ahead cannot perturb them and exact resume is preserved.
"""
from __future__ import annotations

import csv
import logging
import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import DROP_REASONS, AgentConfig
from ..env.driver import EpisodeDriver
from ..env.env import ServiceCoordEnv
from ..obs.trace import episode_span, phase_span
from ..utils.debug import check_invariants
from ..utils.telemetry import PhaseTimer
from .buffer import buffer_nbytes
from .ddpg import DDPG, DDPGState

log = logging.getLogger("gsc_tpu.agents.trainer")


class RewardsWriter:
    """rewards.csv with the live writer's schema (result_writer.py:23: field
    'r')."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._file = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._file = open(path, "w", newline="")
            self._csv = csv.DictWriter(self._file, fieldnames=["r"])
            self._csv.writeheader()

    def write(self, reward: float):
        if self._file:
            self._csv.writerow({"r": reward})
            self._file.flush()

    def close(self):
        if self._file:
            self._file.close()


class Trainer:
    def __init__(self, env: ServiceCoordEnv, driver: EpisodeDriver,
                 agent_cfg: AgentConfig, seed: int = 0,
                 result_dir: Optional[str] = None,
                 tensorboard: bool = False, gnn_impl: str = None,
                 donate: bool = True, obs=None,
                 check_invariants: bool = False):
        self.env = env
        self.driver = driver
        self.agent_cfg = agent_cfg
        self.seed = seed
        # run observability (gsc_tpu.obs.RunObserver): events.jsonl +
        # metrics.json + device gauges + pipeline watchdog.  The trainer
        # only reports into it; lifecycle (start/close) belongs to the
        # caller (cli train wraps the whole run).
        self.obs = obs
        # opt-in per-episode simulator invariant check (utils.debug) —
        # violations surface as structured ``invariant_violation`` events
        # (and WARNs) instead of a silently-returned list
        self.check_invariants = check_invariants
        # donation is on by default: the training loops always rebind the
        # carries from the kernel returns, so in-place HBM updates of the
        # replay/env-state are safe; pass donate=False for comparison
        # drivers that re-call kernels on the same inputs
        self.ddpg = DDPG(env, agent_cfg, gnn_impl=gnn_impl, donate=donate)
        if self.obs is not None:
            # param/compute/replay dtype gauges + one precision event so
            # run-to-run throughput comparisons can attribute speedups to
            # the dtype policy (bench rows carry the same field)
            self.obs.record_precision(agent_cfg.precision_policy)
        self.result_dir = result_dir
        # per-phase host wall timings of the last train() call
        # (utils.telemetry.PhaseTimer) — how much host time hid behind
        # device compute; populated by train(), logged at loop end
        self.phase_timer = None
        self.rewards_writer = RewardsWriter(
            os.path.join(result_dir, "rewards.csv") if result_dir else None)
        self.tb = None
        if tensorboard and result_dir:
            try:  # torch's TB writer, mirroring simple_ddpg.py:165
                from torch.utils.tensorboard import SummaryWriter
                self.tb = SummaryWriter(os.path.join(result_dir, "tb"))
            except ImportError:
                pass
        self.history: List[Dict[str, float]] = []

    def _log(self, episode: int, global_step: int, stats, learn_metrics,
             sps: float):
        row = {k: float(np.asarray(v)) for k, v in stats.items()}
        if learn_metrics is not None:
            row.update({k: float(np.asarray(v))
                        for k, v in learn_metrics.items()})
        row.update(episode=episode, sps=sps)
        self.history.append(row)
        self.rewards_writer.write(row["episodic_return"])
        if self.tb:
            self.tb.add_scalar("charts/episodic_return",
                               row["episodic_return"], global_step)
            self.tb.add_scalar("charts/SPS", sps, global_step)
            if learn_metrics is not None:
                self.tb.add_scalar("losses/qf1_loss", row["critic_loss"],
                                   global_step)
                self.tb.add_scalar("losses/actor_loss", row["actor_loss"],
                                   global_step)
                self.tb.add_scalar("losses/qf1_values", row["q_values"],
                                   global_step)

    def _drain(self, entry, start_time: float, start_episode: int,
               verbose: bool, timer):
        """Sync one pending episode's device metrics to host and log it.
        On the pipelined path this runs one episode BEHIND the dispatch
        head, so the ``np.asarray`` syncs here wait on device work that has
        already been followed by the next episode's dispatch — the chip
        never idles on host-side logging."""
        ep, end_step, stats, learn_metrics, trunc_dev, sim, topo, \
            replay_bytes = entry
        hub = self.obs.hub if self.obs else None
        with phase_span("drain", timer, hub):
            # force the episode's device work complete BEFORE reading the
            # wall clock: sps must divide by time that includes the
            # episode's compute (bench.py's bank() contract), not the
            # async-dispatch return time
            jax.block_until_ready((stats, learn_metrics, trunc_dev))
            steps_per_ep = self.agent_cfg.episode_steps
            sps = ((ep - start_episode + 1) * steps_per_ep
                   / (time.time() - start_time))
            trunc = int(np.asarray(trunc_dev))
            if trunc > 0:
                # overload: the flow table (or per-substep arrival budget)
                # saturated, so some arrivals spawned late — generated-flow
                # timing no longer matches the reference's unbounded model
                log.warning(
                    "episode=%d: %d arrivals admitted late (flow-table "
                    "slot exhaustion) — raise SimConfig.max_flows to "
                    "restore exact arrival timing", ep, trunc)
            self._log(ep, end_step, stats, learn_metrics, sps)
            if verbose:
                # per-episode progress line (the reference's tqdm + SPS
                # TensorBoard log, simple_ddpg.py:269-271) via the package
                # logger — setup_logging routes it to console + run.log
                log.info(
                    "episode=%d return=%.3f succ=%.3f sps=%.1f", ep,
                    float(np.asarray(stats["episodic_return"])),
                    float(np.asarray(stats["mean_succ_ratio"])), sps)
        # observability work sits OUTSIDE the drain span: the drain phase
        # measures time blocked on device→host metric syncs, not host-side
        # bookkeeping — and the emitted event then carries phase totals
        # that include the drain just finished
        if self.check_invariants:
            # promoted from utils.debug: per drained episode, the final
            # sim state is checked host-side and violations become
            # structured events rather than a silently-returned list.
            # (check_invariants is a module-level import — a per-episode
            # lazy import here cost an import-system round-trip inside
            # the drain path, flagged by gsc-lint's hot-loop review.)
            errs = check_invariants(sim, topo, self.env.tables.chain_len)
            if errs:
                log.warning("episode=%d simulator invariants violated: %s",
                            ep, "; ".join(errs))
                if self.obs:
                    # routed through the sentinel event pathway (counter +
                    # structured event), same family as `compile` events
                    self.obs.invariant_violation(ep, errs)
        if self.obs:
            row = self.history[-1]
            self.obs.episode_end(
                episode=ep, global_step=end_step,
                metrics={k: v for k, v in row.items()
                         if k not in ("episode", "sps")},
                sps=sps, phases=timer.summary(),
                drop_reasons=dict(zip(
                    DROP_REASONS,
                    np.asarray(sim.metrics.drop_reasons).tolist())),
                truncated_arrivals=trunc, replay_bytes=replay_bytes)

    def train(self, episodes: int, test_mode: bool = False,
              verbose: bool = False, profile: bool = False,
              init_state: Optional[DDPGState] = None,
              init_buffer=None, start_episode: int = 0,
              pipeline: bool = True):
        """Train through episode ``episodes - 1`` (train-at-episode-end
        schedule, simple_ddpg.py:280-329).  Returns (final learner state,
        replay buffer).  With ``profile`` a jax profiler trace of the run is
        written to <result_dir>/profile (SURVEY.md §5 tracing analogue).

        ``pipeline=True`` (default) runs the asynchronous episode pipeline:
        prefetched host traffic, one fused rollout+learn device call per
        episode, and metric draining deferred one episode behind dispatch.
        ``pipeline=False`` is the serial reference loop (two device calls
        per episode, synced logging) — results are bit-identical either
        way; the flag only changes host/device scheduling.

        Exact resume: pass a restored (``init_state``, ``init_buffer``,
        ``start_episode``) triple and the continuation reproduces an
        uninterrupted run bit-for-bit — per-episode keys derive from
        ``fold_in(seed, episode)`` rather than a sequential split chain, so
        the host-side stream needs no replay (the device-side stream lives
        in DDPGState.rng, which the checkpoint carries).  The reference
        cannot do this: it never saves optimizer or replay state
        (main.py:46-50, SURVEY.md §5)."""
        if profile and self.result_dir:
            from ..utils.debug import Profiler
            with Profiler(os.path.join(self.result_dir, "profile")):
                return self.train(episodes, test_mode, verbose,
                                  profile=False, init_state=init_state,
                                  init_buffer=init_buffer,
                                  start_episode=start_episode,
                                  pipeline=pipeline)
        self.phase_timer = timer = PhaseTimer()
        hub = self.obs.hub if self.obs else None
        base = jax.random.PRNGKey(self.seed)
        steps_per_ep = self.agent_cfg.episode_steps

        if self.ddpg.donate:
            # restored carries (orbax checkpoints, caller-held pytrees) may
            # alias each other or host-owned storage; donation needs
            # exclusively-owned device buffers — donating a restored state
            # aborts the process on the CPU backend (pending_donation_
            # check).  Re-materialize once before the first donated
            # dispatch, mirroring init()'s target-aliasing break.
            if init_state is not None:
                init_state = jax.tree_util.tree_map(jnp.copy, init_state)
            if init_buffer is not None:
                init_buffer = jax.tree_util.tree_map(jnp.copy, init_buffer)

        prefetch = None
        if pipeline:
            # traffic staged to device FROM THE PREFETCH THREAD, so the
            # host→device transfer also overlaps the running episode; the
            # topology object passes through untouched (it is the driver's
            # cached pytree — id()-keyed caches downstream rely on that)
            # stop bound covers the unconditional initial sample even when
            # the episode range is empty (the serial loop's behavior)
            prefetch = self.driver.prefetcher(
                start_episode, max(episodes, start_episode + 1), test_mode,
                stage=lambda topo, traffic: (topo, jax.device_put(traffic)),
                heartbeat=(self.obs.prefetcher_heartbeat()
                           if self.obs else None))
            if self.obs:
                self.obs.attach_prefetcher(prefetch)
        if self.obs:
            # arm the stall monitor only while the episode loop runs —
            # compile/eval/checkpoint time is not a pipeline stall
            self.obs.resume_watchdog()

        def next_episode(ep):
            if prefetch is not None:
                # blocks only when the producer thread is behind — i.e.
                # host sampling is the true bottleneck, not the sync order
                with phase_span("host_sample_wait", timer, hub):
                    return prefetch.get(ep)
            with phase_span("host_sample", timer, hub):
                return self.driver.episode(ep, test_mode)

        pending = []  # dispatched episodes whose metrics are not yet synced
        # serial path drains immediately (the seed behavior); pipelined
        # drains lag one episode so the sync never gates the next dispatch
        max_pending = 1 if pipeline else 0
        try:
            topo, traffic = next_episode(start_episode)
            env_state, obs = self.env.reset(
                jax.random.fold_in(base, 1000 + start_episode), topo,
                traffic)
            state = init_state if init_state is not None else \
                self.ddpg.init(jax.random.fold_in(base, 0), obs)
            buffer = init_buffer if init_buffer is not None else \
                self.ddpg.init_buffer(obs)
            # replay residency is static across the run (ring buffer):
            # computed once from shapes, streamed in every episode event
            replay_bytes = buffer_nbytes(buffer)
            if verbose:
                log.info(
                    "replay buffer: %.1f MiB resident%s",
                    replay_bytes / 2 ** 20,
                    " — donated, updated in place each episode"
                    if self.ddpg.donate else
                    " — copied each episode (donate=False)")

            start = time.time()
            for ep in range(start_episode, episodes):
                if ep > start_episode:
                    topo, traffic = next_episode(ep)
                    env_state, obs = self.env.reset(
                        jax.random.fold_in(base, 1000 + ep), topo, traffic)
                global_step = ep * steps_per_ep
                end_step = global_step + steps_per_ep - 1
                learn = (end_step
                         >= self.agent_cfg.nb_steps_warmup_critic - 1)
                with phase_span("dispatch", timer, hub), episode_span(ep):
                    if pipeline:
                        (state, buffer, env_state, obs, stats,
                         learn_metrics) = self.ddpg.episode_step(
                            state, buffer, env_state, obs, topo, traffic,
                            np.int32(global_step), learn=learn)
                    else:
                        (state, buffer, env_state, obs,
                         stats) = self.ddpg.rollout_episode(
                            state, buffer, env_state, obs, topo, traffic,
                            np.int32(global_step))
                        learn_metrics = None
                        if learn:
                            state, learn_metrics = self.ddpg.learn_burst(
                                state, buffer)
                if self.obs:
                    self.obs.episode_dispatched(ep)
                # the retained arrays (stats, learn metrics, the truncation
                # scalar, and the episode-final sim state the obs/invariant
                # layer reads) are plain kernel outputs — never donated
                # (the NEXT episode's env_state comes from a fresh
                # env.reset, not this one), so deferring their sync is
                # safe under buffer donation
                pending.append((ep, end_step, stats, learn_metrics,
                                env_state.sim.truncated_arrivals,
                                env_state.sim, topo, replay_bytes))
                while len(pending) > max_pending:
                    self._drain(pending.pop(0), start, start_episode,
                                verbose, timer)
            while pending:
                # happy-path tail drain stays INSIDE the try: an async
                # device fault surfacing at the final episode's sync must
                # raise like the serial loop would, not be downgraded
                self._drain(pending.pop(0), start, start_episode, verbose,
                            timer)
        finally:
            if self.obs:
                # disarm BEFORE the best-effort teardown drains — a fault
                # recovery path must not also spray stall events
                self.obs.pause_watchdog()
            # only nonempty when an exception is already propagating:
            # flush completed episodes' rows into rewards.csv exactly as
            # the serial loop would have written them before the fault.
            # Best effort — a drain that itself fails (device in a bad
            # state) must not mask the original exception.
            while pending:
                entry = pending.pop(0)
                try:
                    self._drain(entry, start, start_episode, verbose,
                                timer)
                except Exception:
                    log.warning("dropping metrics of episode %d: drain "
                                "failed after a faulted dispatch", entry[0])
                    break
            if prefetch is not None:
                prefetch.close()
        if verbose:
            log.info("pipeline phase timings: %s", timer.summary())
        self.rewards_writer.close()
        if self.tb:
            self.tb.close()
        return state, buffer

    def train_parallel(self, episodes: int, num_replicas: int,
                       chunk: int = 50, verbose: bool = False,
                       device_traffic: bool = True, profile: bool = False,
                       init_state: Optional[DDPGState] = None,
                       init_buffers=None, start_episode: int = 0):
        """Replica-parallel training: B vmapped env replicas per episode on
        the scheduled topology, chunked rollouts + end-of-episode learn
        burst (the bench/learning-curve path), logged through the same
        rewards.csv/history machinery as ``train``.  Per-episode traffic is
        sampled ON DEVICE by default (one DeviceTraffic sampler per
        distinct scheduled topology).  Returns (state, buffers).

        The reference has no analogue (one process, one env); evaluation
        and checkpointing consume the resulting learner state exactly like
        the single-env path's."""
        if profile and self.result_dir:
            from ..utils.debug import Profiler
            with Profiler(os.path.join(self.result_dir, "profile")):
                return self.train_parallel(episodes, num_replicas, chunk,
                                           verbose, device_traffic,
                                           profile=False,
                                           init_state=init_state,
                                           init_buffers=init_buffers,
                                           start_episode=start_episode)
        from ..parallel import ParallelDDPG
        from ..parallel.harness import run_chunked_episodes
        from ..sim.traffic_device import DeviceTraffic

        steps_per_ep = self.agent_cfg.episode_steps
        if steps_per_ep % chunk != 0:
            # never silently upgrade to a single full-episode scan — that
            # is exactly the call shape the chunking exists to avoid
            raise ValueError(
                f"chunk ({chunk}) must divide episode_steps "
                f"({steps_per_ep})")
        pddpg = ParallelDDPG(self.env, self.agent_cfg,
                             num_replicas=num_replicas, donate=True,
                             gnn_impl=self.ddpg.actor.gnn_impl)
        base = jax.random.PRNGKey(self.seed)
        # restored carries must be re-materialized before donation — see
        # train(): donating orbax-restored (host-owned / aliased) buffers
        # aborts the process
        if init_state is not None:
            init_state = jax.tree_util.tree_map(jnp.copy, init_state)
        if init_buffers is not None:
            init_buffers = jax.tree_util.tree_map(jnp.copy, init_buffers)

        topo0, traffic0 = self.driver.episode(0, False)
        _, one_obs = self.env.reset(jax.random.fold_in(base, 1000), topo0,
                                    traffic0)
        state = init_state if init_state is not None else \
            pddpg.init(jax.random.fold_in(base, 0), one_obs)
        buffers = init_buffers if init_buffers is not None else \
            pddpg.init_buffers(one_obs)

        # one on-device sampler per scheduled topology (the scheduler
        # cycles training_network_files every `period` episodes)
        samplers = {}

        def episode_traffic(ep, topo):
            if not device_traffic:
                stacked = [self.driver.traffic_for(
                    ep, topo, seed=self.driver.base_seed + 1000 * ep + r)
                    for r in range(num_replicas)]
                return jax.tree_util.tree_map(
                    lambda *xs: jax.numpy.stack(xs), *stacked)
            # key by the topology OBJECT the episode actually uses — the
            # driver owns the schedule; re-deriving its index here would
            # duplicate that invariant
            if id(topo) not in samplers:
                samplers[id(topo)] = DeviceTraffic(
                    self.env.sim_cfg, self.env.service, topo, steps_per_ep,
                    trace=self.driver.trace, capacity=self.driver.capacity)
            return samplers[id(topo)].sample_batch(
                jax.random.fold_in(base, 2000 + ep), num_replicas)

        self.phase_timer = timer = PhaseTimer()
        hub = self.obs.hub if self.obs else None
        if self.obs:
            self.obs.resume_watchdog()
        start = time.time()
        try:
            # the scheduler may swap topologies mid-run, so drive the
            # harness one episode at a time with that episode's topology —
            # passing the GLOBAL step offset so the agent's warmup schedule
            # sees one continuous run (and a resumed run continues it
            # exactly)
            for ep in range(start_episode, episodes):
                topo = self.driver.topology_for(ep)
                traffic = episode_traffic(ep, topo)
                if self.obs:
                    self.obs.episode_dispatched(ep)
                state, buffers, rets, succ, final = run_chunked_episodes(
                    pddpg, topo, lambda _: traffic, state, buffers,
                    1, steps_per_ep, chunk, self.seed + ep,
                    step_offset=ep * steps_per_ep, hub=hub, timer=timer)
                sps = ((ep - start_episode + 1) * steps_per_ep
                       * num_replicas / (time.time() - start))
                row = {"episodic_return": rets[0],
                       "mean_succ_ratio": succ[0],
                       "final_succ_ratio": final[0], "episode": ep,
                       "sps": sps}
                self.history.append(row)
                self.rewards_writer.write(rets[0])
                if self.tb:
                    gs = (ep + 1) * steps_per_ep
                    self.tb.add_scalar("charts/episodic_return", rets[0], gs)
                    self.tb.add_scalar("charts/SPS", sps, gs)
                if verbose:
                    log.info("episode=%d return=%.3f succ=%.3f sps=%.1f",
                             ep, rets[0], succ[0], sps)
                if self.obs:
                    self.obs.episode_end(
                        episode=ep, global_step=(ep + 1) * steps_per_ep - 1,
                        metrics={k: v for k, v in row.items()
                                 if k not in ("episode", "sps")},
                        sps=sps, phases=timer.summary(),
                        replay_bytes=buffer_nbytes(buffers),
                        extra={"replicas": num_replicas})
        finally:
            if self.obs:
                self.obs.pause_watchdog()
        self.rewards_writer.close()
        if self.tb:
            self.tb.close()
        return state, buffers

    def evaluate(self, state: DDPGState, episodes: int = 1,
                 test_mode: bool = True, telemetry: bool = False,
                 write_schedule: bool = False,
                 telemetry_flush_every: int = 1) -> Dict[str, float]:
        """Greedy rollout on the inference network (inference.py:17-40
        semantics: actor only, no noise, no learning).  With ``telemetry``
        the reference's test-mode CSV suite is written to
        <result_dir>/test (writer.py:16-110 schema);
        ``telemetry_flush_every`` batches the suite's per-interval file
        flushes for long sweeps (default 1 = reference behavior)."""
        writer = None
        if telemetry and self.result_dir:
            from ..utils.telemetry import TestModeWriter
            writer = TestModeWriter(
                os.path.join(self.result_dir, "test"),
                write_schedule=write_schedule,
                sf_names=self.env.service.sf_names,
                sfc_names=self.env.service.sfc_names,
                flush_every=telemetry_flush_every)
        totals = []
        succ = []
        for ep in range(episodes):
            t_ep = time.time()
            topo, traffic = self.driver.episode(ep, test_mode)
            rng = jax.random.PRNGKey(self.seed + 10_000 + ep)
            env_state, obs = self.env.reset(rng, topo, traffic)
            ep_reward = 0.0
            infos = None
            for _ in range(self.agent_cfg.episode_steps):
                t0 = time.time()
                action = self.ddpg.actor.apply(state.actor_params, obs)
                action = jax.numpy.clip(action, 0.0, 1.0)
                action = self.env.process_action(action)
                # algorithm runtime per control step (the adapter's
                # measurement between calls, siminterface/simulator.py:161-167);
                # block so async dispatch doesn't hide the compute time
                jax.block_until_ready(action)
                runtime = time.time() - t0
                env_state, obs, reward, done, infos = self.env.step(
                    env_state, topo, traffic, action)
                ep_reward += float(np.asarray(reward))
                if writer:
                    # the schedule/placement the env actually applied,
                    # surfaced by env.step (no recomputation)
                    sched = infos["schedule"]
                    placement = infos["placement"]
                    t_steps = traffic.ingress_active.shape[0]
                    idx = min(int(env_state.sim.run_idx) - 1, t_steps - 1)
                    flat = (np.asarray(obs).tolist()
                            if not self.agent_cfg.graph_mode else
                            np.asarray(obs.nodes).T.reshape(-1).tolist())
                    writer.write_step(
                        episode=ep, time=float(env_state.sim.t),
                        metrics=env_state.sim.metrics, placement=placement,
                        node_cap=traffic.node_cap[max(idx, 0)],
                        schedule=sched, runtime=runtime, rl_state=flat,
                        truncated_arrivals=int(np.asarray(
                            env_state.sim.truncated_arrivals)))
            totals.append(ep_reward)
            succ.append(float(np.asarray(infos["succ_ratio"])))
            if self.obs:
                # greedy test rollouts stream through the same hub — a
                # long eval sweep is visible (and device memory sampled)
                # just like training episodes
                self.obs.eval_episode(ep, ep_reward, succ[-1],
                                      time.time() - t_ep)
        if writer:
            writer.close()
        return {"mean_return": float(np.mean(totals)),
                "final_succ_ratio": float(np.mean(succ))}
