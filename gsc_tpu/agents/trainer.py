"""Training driver — the host loop around the jitted rollout/learn kernels.

The analogue of SimpleDDPG.train + the experiment plumbing of
src/rlsp/agents/main.py: per episode it picks the scheduled topology,
samples traffic (host), dispatches the episode's device work, and logs
episode metrics (rewards.csv like result_writer.py:6-38, optional
TensorBoard like simple_ddpg.py:165-174).

The default ``pipeline=True`` path keeps the accelerator saturated between
episodes (Podracer-style, arXiv:2104.06272): a background thread PREFETCHES
episode k+1's topology/traffic (staged to device) while episode k runs, the
rollout scan and learn burst run as ONE fused jitted ``episode_step`` (no
host round-trip between them), per-episode metric syncs are DEFERRED one
episode so ``np.asarray`` never gates the next dispatch, and the replay
buffer / env-state carries are donated (updated in place in HBM instead of
copied every episode).  Results are bit-identical to the serial path —
per-episode PRNG streams are ``fold_in``-keyed by the episode index, so
look-ahead cannot perturb them and exact resume is preserved.
"""
from __future__ import annotations

import csv
import logging
import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import DROP_REASONS, AgentConfig
from ..env.driver import EpisodeDriver
from ..env.env import ServiceCoordEnv
from ..obs.trace import episode_span, phase_span
from ..resilience.faults import FaultInjected
from ..resilience.guard import RollbackGuard, poison_tree
from ..resilience.retry import (RetryPolicy, TransientDispatchError,
                                call_with_retry)
from ..utils.debug import check_invariants
from ..utils.telemetry import PhaseTimer
from .buffer import buffer_nbytes
from .ddpg import DDPG, DDPGState

log = logging.getLogger("gsc_tpu.agents.trainer")


class RewardsWriter:
    """rewards.csv with the live writer's schema (result_writer.py:23: field
    'r')."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._file = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._file = open(path, "w", newline="")
            self._csv = csv.DictWriter(self._file, fieldnames=["r"])
            self._csv.writeheader()

    def write(self, reward: float):
        if self._file:
            self._csv.writerow({"r": reward})
            self._file.flush()

    def close(self):
        if self._file:
            self._file.close()


class Trainer:
    def __init__(self, env: ServiceCoordEnv, driver: EpisodeDriver,
                 agent_cfg: AgentConfig, seed: int = 0,
                 result_dir: Optional[str] = None,
                 tensorboard: bool = False, gnn_impl: str = None,
                 donate: bool = True, obs=None,
                 check_invariants: bool = False,
                 fault_plan=None, rollback: bool = True,
                 retry_policy: Optional[RetryPolicy] = None,
                 pipeline_fault_limit: int = 3):
        self.env = env
        self.driver = driver
        self.agent_cfg = agent_cfg
        self.seed = seed
        # --- resilience (gsc_tpu.resilience) -------------------------
        # fault_plan: deterministic injection schedule (FaultPlan) — None
        # in production; every recovery path below has a test through it
        self.fault_plan = fault_plan
        # rollback=True keeps a last-good in-memory snapshot of the
        # (state, replay) carries and restores it when the on-device
        # all-finite guard flags a poisoned learner state.  Costs two
        # device-side pytree copies per episode + ~2 retained replay
        # copies in HBM; with no violation the training math is
        # bit-identical either way (copies never enter the update path).
        self.rollback = rollback
        self.retry_policy = retry_policy or RetryPolicy()
        # pipeline faults (prefetcher death / watchdog-escalation
        # interrupts) beyond this limit degrade pipeline -> off for the
        # remainder of the run: serial host sampling + immediate drains
        # (the fused dispatch kernel itself is unaffected)
        self.pipeline_fault_limit = pipeline_fault_limit
        # set by train()/train_parallel(): episodes completed when the
        # loop exited (monotone resume counter) and whether a preemption
        # guard stopped it early — the CLI checkpoints off these
        self.completed_episodes = 0
        self.preempted = False
        self._last_drained = -1
        self._live_prefetch = None   # watchdog-escalation interrupt target
        # run observability (gsc_tpu.obs.RunObserver): events.jsonl +
        # metrics.json + device gauges + pipeline watchdog.  The trainer
        # only reports into it; lifecycle (start/close) belongs to the
        # caller (cli train wraps the whole run).
        self.obs = obs
        # opt-in per-episode simulator invariant check (utils.debug) —
        # violations surface as structured ``invariant_violation`` events
        # (and WARNs) instead of a silently-returned list
        self.check_invariants = check_invariants
        # learning-signal ledger (obs.learning): when the observer owns a
        # LearnLedger, thread its STATIC spec into the jitted agents so
        # the dispatched programs fold per-topology |TD| segments, Q
        # moments, layer norms and replay stats into their existing
        # outputs.  No observer / bare observer => spec None => the
        # historic traces, byte for byte.
        self.learn_obs = getattr(obs, "learn", None) \
            if obs is not None else None
        ledger_spec = None
        if self.learn_obs is not None:
            ledger_spec = self.learn_obs.spec(
                getattr(driver, "num_topo_ids", 1),
                getattr(driver, "topo_id_names", None))
        # donation is on by default: the training loops always rebind the
        # carries from the kernel returns, so in-place HBM updates of the
        # replay/env-state are safe; pass donate=False for comparison
        # drivers that re-call kernels on the same inputs
        self.ddpg = DDPG(env, agent_cfg, gnn_impl=gnn_impl, donate=donate,
                         learn_ledger=ledger_spec)
        if self.obs is not None:
            # param/compute/replay dtype gauges + one precision event so
            # run-to-run throughput comparisons can attribute speedups to
            # the dtype policy (bench rows carry the same field)
            self.obs.record_precision(agent_cfg.precision_policy)
        self.result_dir = result_dir
        # per-phase host wall timings of the last train() call
        # (utils.telemetry.PhaseTimer) — how much host time hid behind
        # device compute; populated by train(), logged at loop end
        self.phase_timer = None
        self.rewards_writer = RewardsWriter(
            os.path.join(result_dir, "rewards.csv") if result_dir else None)
        self.tb = None
        if tensorboard and result_dir:
            try:  # torch's TB writer, mirroring simple_ddpg.py:165
                from torch.utils.tensorboard import SummaryWriter
                self.tb = SummaryWriter(os.path.join(result_dir, "tb"))
            except ImportError:
                pass
        self.history: List[Dict[str, float]] = []

    def _log(self, episode: int, global_step: int, stats, learn_metrics,
             sps: float):
        row = {k: float(np.asarray(v)) for k, v in stats.items()}
        if learn_metrics is not None:
            row.update({k: float(np.asarray(v))
                        for k, v in learn_metrics.items()})
        row.update(episode=episode, sps=sps)
        self.history.append(row)
        self.rewards_writer.write(row["episodic_return"])
        if self.tb:
            self.tb.add_scalar("charts/episodic_return",
                               row["episodic_return"], global_step)
            self.tb.add_scalar("charts/SPS", sps, global_step)
            if learn_metrics is not None:
                self.tb.add_scalar("losses/qf1_loss", row["critic_loss"],
                                   global_step)
                self.tb.add_scalar("losses/actor_loss", row["actor_loss"],
                                   global_step)
                self.tb.add_scalar("losses/qf1_values", row["q_values"],
                                   global_step)

    def _drain(self, entry, start_time: float, start_episode: int,
               verbose: bool, timer) -> bool:
        """Sync one pending episode's device metrics to host and log it.
        On the pipelined path this runs one episode BEHIND the dispatch
        head, so the ``np.asarray`` syncs here wait on device work that has
        already been followed by the next episode's dispatch — the chip
        never idles on host-side logging.

        Returns the episode's all-finite verdict (the on-device guard
        flags computed inside ``episode_step``, drained here with the
        other deferred metrics): False means the learner state this
        episode saw or produced is poisoned and the caller should roll
        back."""
        ep, end_step, stats, learn_metrics, trunc_dev, sim, topo, \
            replay_bytes = entry
        hub = self.obs.hub if self.obs else None
        finite = True
        with phase_span("drain", timer, hub):
            # force the episode's device work complete BEFORE reading the
            # wall clock: sps must divide by time that includes the
            # episode's compute (bench.py's bank() contract), not the
            # async-dispatch return time
            jax.block_until_ready((stats, learn_metrics, trunc_dev))
            # learn-ledger extras are non-scalar (TD segment vectors,
            # layer-norm dicts): split them off before the scalar row
            # conversion below — already synced by the block above, so
            # the host-side emit later reads them for free
            replay = stats.pop("replay", None) \
                if isinstance(stats, dict) else None
            signal = learn_metrics.pop("learn_signal", None) \
                if isinstance(learn_metrics, dict) else None
            steps_per_ep = self.agent_cfg.episode_steps
            sps = ((ep - start_episode + 1) * steps_per_ep
                   / (time.time() - start_time))
            trunc = int(np.asarray(trunc_dev))
            if trunc > 0:
                # overload: the flow table (or per-substep arrival budget)
                # saturated, so some arrivals spawned late — generated-flow
                # timing no longer matches the reference's unbounded model
                log.warning(
                    "episode=%d: %d arrivals admitted late (flow-table "
                    "slot exhaustion) — raise SimConfig.max_flows to "
                    "restore exact arrival timing", ep, trunc)
            # divergence verdict: the rollout flag covers the state the
            # episode STARTED from, the learn flag the post-update state
            # — both already synced by the block above, so these asarray
            # reads are free
            if "state_finite" in stats:
                finite = bool(np.asarray(stats["state_finite"]) > 0)
            if learn_metrics is not None \
                    and "state_finite" in learn_metrics:
                finite = finite and bool(
                    np.asarray(learn_metrics["state_finite"]) > 0)
            self._log(ep, end_step, stats, learn_metrics, sps)
            if verbose:
                # per-episode progress line (the reference's tqdm + SPS
                # TensorBoard log, simple_ddpg.py:269-271) via the package
                # logger — setup_logging routes it to console + run.log
                log.info(
                    "episode=%d return=%.3f succ=%.3f sps=%.1f", ep,
                    float(np.asarray(stats["episodic_return"])),
                    float(np.asarray(stats["mean_succ_ratio"])), sps)
        # observability work sits OUTSIDE the drain span: the drain phase
        # measures time blocked on device→host metric syncs, not host-side
        # bookkeeping — and the emitted event then carries phase totals
        # that include the drain just finished
        if self.check_invariants:
            # promoted from utils.debug: per drained episode, the final
            # sim state is checked host-side and violations become
            # structured events rather than a silently-returned list.
            # (check_invariants is a module-level import — a per-episode
            # lazy import here cost an import-system round-trip inside
            # the drain path, flagged by gsc-lint's hot-loop review.)
            errs = check_invariants(sim, topo, self.env.tables.chain_len)
            if errs:
                log.warning("episode=%d simulator invariants violated: %s",
                            ep, "; ".join(errs))
                if self.obs:
                    # routed through the sentinel event pathway (counter +
                    # structured event), same family as `compile` events
                    self.obs.invariant_violation(ep, errs)
        if self.obs:
            row = self.history[-1]
            # topology identity on the SERIAL path too: mixed batches get
            # per-replica names through the harness, but a single-replica
            # run's episodes must land in the same per-topology report
            # tables — stamp the scheduled network's name on the event
            # and gauge its return
            extra = self._topology_extra(ep, row["episodic_return"])
            self.obs.episode_end(
                episode=ep, global_step=end_step,
                metrics={k: v for k, v in row.items()
                         if k not in ("episode", "sps")},
                sps=sps, phases=timer.summary(),
                drop_reasons=dict(zip(
                    DROP_REASONS,
                    np.asarray(sim.metrics.drop_reasons).tolist())),
                truncated_arrivals=trunc, replay_bytes=replay_bytes,
                extra=extra)
            if self.learn_obs is not None and (signal is not None
                                               or replay is not None):
                # drained learning signal -> learn_signal event + gauges
                # (values synced above; nothing here waits on the device)
                self.learn_obs.episode(ep, signal=signal, replay=replay)
        return finite

    # ---------------------------------------------------------- resilience
    def _recover(self, episode: int, site: str, action: str,
                 fault: Optional[str] = None, attempt: Optional[int] = None,
                 detail: Optional[str] = None):
        """Log + emit one structured ``recovery`` event (obs.RunObserver)
        for a self-healing action — the recovery timeline every fault
        path below reports through."""
        log.warning("recovery: site=%s action=%s episode=%s fault=%s%s",
                    site, action, episode, fault,
                    f" ({detail})" if detail else "")
        if self.obs is not None:
            self.obs.recovery(episode=episode, site=site, action=action,
                              fault=fault, attempt=attempt, detail=detail)

    def _topology_extra(self, episode: int, episodic_return,
                        extra: Optional[Dict] = None) -> Optional[Dict]:
        """Topology identity for one drained episode (BOTH train paths):
        gauge ``topology_return{topology=<name>}`` and return the episode
        event's ``extra`` dict with the name stamped in — the one rule
        behind the serial drain and the homogeneous replica loop, so the
        per-topology tables obs_report merges can never diverge between
        them.  No-op (returns ``extra`` unchanged) without an observer or
        a nameable driver."""
        namer = getattr(self.driver, "topology_name_for", None)
        name = namer(episode) if namer is not None else None
        if not name or self.obs is None:
            return extra
        self.obs.hub.gauge("topology_return", float(episodic_return),
                           topology=name)
        return {**(extra or {}), "topology": name}

    @staticmethod
    def _finite_host(tree) -> bool:
        """Host-side all-finite scan over a (host-layout) pytree's
        inexact leaves — the replica path's stand-in for the rollback
        guard's on-device verdict.  ONE definition shared by the
        periodic-checkpoint and hot-swap-publish gates in
        ``train_parallel``, so the two paths can never diverge on what
        counts as a poisoned state."""
        return all(np.isfinite(np.asarray(leaf)).all()
                   for leaf in jax.tree_util.tree_leaves(tree)
                   if np.issubdtype(np.asarray(leaf).dtype, np.inexact))

    # -------------------------------------------------------- cost ledger
    @staticmethod
    def _ledger_fn(owner, name: str):
        """The dispatched-executable resolver (obs.perf.resolve_lowerable)
        — kept as a method so both train paths read the same way."""
        from ..obs.perf import resolve_lowerable
        return resolve_lowerable(owner, name)

    def _capture_costs(self, names_args: Dict[str, tuple]):
        """Feed the observer's device-cost ledger (obs.perf.CostLedger):
        AOT-lower each watched entry point ONCE, before the episode loop,
        so FLOPs/bytes/fusion counts are captured at compile time and the
        dispatch path itself stays sync-free.  ``names_args`` maps entry
        name -> (fn, args, kwargs); lowering never executes the program,
        so passing the live (donation-bound) carries is safe.  Best
        effort: a cost-model failure is a warning, never a dead run."""
        perf = getattr(self.obs, "perf", None) if self.obs else None
        if perf is None:
            return
        for name, (fn, args, kwargs) in names_args.items():
            perf.capture(name, fn, args, kwargs)

    def _note_cost_timings(self, timer, primary: Optional[str]):
        """Merge the run's measured host wall into the ledger AFTER the
        loop: the ``dispatch`` phase total attributes to the primary
        FUSED entry point (its calls are exactly what the phase wraps),
        and the full phase summary rides along as the device-vs-host
        split.  ``primary=None`` on the serial two-call path — there the
        dispatch phase covers rollout AND learn burst, and splitting it
        per entry would fabricate MFU numbers, so serial runs keep
        static costs + phases only."""
        perf = getattr(self.obs, "perf", None) if self.obs else None
        if perf is None or timer is None:
            return
        phases = timer.summary()
        disp = phases.get("dispatch")
        if primary is not None and disp:
            perf.note_timing(primary, disp["total_s"], disp["count"])
            if perf.has(f"{primary}_sharded"):
                # under a plan the dispatched program IS the partitioned
                # executable — the same dispatch wall attributes to its
                # capture too, so its MFU/roofline derive from the HLO
                # that actually ran (the plain entry keeps the
                # carving-comparable number)
                perf.note_timing(f"{primary}_sharded", disp["total_s"],
                                 disp["count"])
        perf.note_phases(phases)

    def _prefetch_fault_hook(self):
        """``before_episode`` hook for the prefetcher's producer thread —
        the injection point of the two producer-side fault sites."""
        plan = self.fault_plan
        if plan is None:
            return None

        def hook(ep: int, stop_event):
            spec = plan.fire("slow_episode", ep)
            if spec is not None:
                # interruptible: wakes the moment close() abandons this
                # producer (so an escalation-triggered restart is not
                # gated on the full injected delay)
                stop_event.wait(spec.arg if spec.arg is not None else 1.0)
            spec = plan.fire("prefetch_die", ep)
            if spec is not None:
                raise FaultInjected(
                    f"injected prefetcher death at episode {ep}")
        return hook

    def _on_watchdog_escalate(self, age: float):
        """Watchdog escalation callback (runs on the watchdog thread):
        interrupt the live prefetcher so the training loop — possibly
        blocked inside ``prefetch.get`` — wakes with a
        ``PrefetchInterrupted`` and restarts it from the episode counter
        (safe: the pipeline is bit-identical to serial sampling, so
        re-staging an episode reproduces it exactly)."""
        pf = self._live_prefetch
        if pf is not None:
            pf.interrupt(f"watchdog escalation: no completed episode in "
                         f"{age:.1f}s")

    def _dispatch_with_retry(self, ep, pipeline, state, buffer, env_state,
                             obs, topo, traffic, global_step, learn, timer,
                             hub):
        """One episode's device dispatch under the bounded-backoff retry
        policy.  Returns the 6-tuple (state, buffer, env_state, obs,
        stats, learn_metrics) on both dispatch shapes.

        The injected ``dispatch_transient`` fault raises at call entry —
        before the kernels consume any donated carry — so a retry
        re-dispatches untouched buffers; a REAL transient that aborted
        mid-program may have invalidated them, in which case the retry
        fails fast with XLA's donation error and propagates (see
        resilience.retry)."""
        plan = self.fault_plan

        # one donating call site per function scope: gsc-lint's R2
        # use-after-donation scan is linear and would read the serial
        # branch's rollout_episode(state, ...) as a use after the fused
        # branch's episode_step donated `state` — mutually exclusive
        # branches, but split closures make that obvious to the tool too
        def dispatch_fused():
            with phase_span("dispatch", timer, hub), episode_span(ep):
                return self.ddpg.episode_step(
                    state, buffer, env_state, obs, topo, traffic,
                    np.int32(global_step), learn=learn)

        def dispatch_serial():
            with phase_span("dispatch", timer, hub), episode_span(ep):
                st, buf, es, ob, stats = self.ddpg.rollout_episode(
                    state, buffer, env_state, obs, topo, traffic,
                    np.int32(global_step))
                metrics = None
                if learn:
                    st, metrics = self.ddpg.learn_burst(st, buf)
                return st, buf, es, ob, stats, metrics

        body = dispatch_fused if pipeline else dispatch_serial

        def dispatch():
            if plan is not None:
                spec = plan.fire("dispatch_transient", ep)
                if spec is not None:
                    raise TransientDispatchError(
                        "injected transient dispatch failure at episode "
                        f"{ep}")
            return body()

        return call_with_retry(
            dispatch, self.retry_policy,
            on_retry=lambda attempt, exc, delay: self._recover(
                ep, site="dispatch", action="retry", fault=repr(exc),
                attempt=attempt,
                detail=f"backing off {delay:.2f}s before re-dispatch"))

    def train(self, episodes: int, test_mode: bool = False,
              verbose: bool = False, profile: bool = False,
              init_state: Optional[DDPGState] = None,
              init_buffer=None, start_episode: int = 0,
              pipeline: bool = True, ckpt_manager=None,
              ckpt_interval: int = 0, preempt=None,
              publisher=None, publish_interval: int = 0):
        """Train through episode ``episodes - 1`` (train-at-episode-end
        schedule, simple_ddpg.py:280-329).  Returns (final learner state,
        replay buffer).  With ``profile`` a jax profiler trace of the run is
        written to <result_dir>/profile (SURVEY.md §5 tracing analogue).

        ``pipeline=True`` (default) runs the asynchronous episode pipeline:
        prefetched host traffic, one fused rollout+learn device call per
        episode, and metric draining deferred one episode behind dispatch.
        ``pipeline=False`` is the serial reference loop (two device calls
        per episode, synced logging) — results are bit-identical either
        way; the flag only changes host/device scheduling.

        Exact resume: pass a restored (``init_state``, ``init_buffer``,
        ``start_episode``) triple and the continuation reproduces an
        uninterrupted run bit-for-bit — per-episode keys derive from
        ``fold_in(seed, episode)`` rather than a sequential split chain, so
        the host-side stream needs no replay (the device-side stream lives
        in DDPGState.rng, which the checkpoint carries).  The reference
        cannot do this: it never saves optimizer or replay state
        (main.py:46-50, SURVEY.md §5).

        Self-healing (gsc_tpu.resilience), every action a structured
        ``recovery`` event:

        - transient dispatch failures retry with bounded exponential
          backoff (``Trainer(retry_policy=...)``);
        - a dead/interrupted prefetcher is restarted from the episode
          counter (bit-identical re-staging), and past
          ``pipeline_fault_limit`` faults the run degrades pipeline->off;
        - a non-finite learner state (on-device guard flags drained with
          the deferred metrics) rolls back to the last-good snapshot and
          skips the poisoned episode(s);
        - ``ckpt_manager`` + ``ckpt_interval`` write checksummed periodic
          checkpoints of the last VERIFIED state;
        - ``preempt`` (a resilience.PreemptionGuard) stops the loop at the
          next episode boundary after SIGTERM/SIGINT — the caller then
          snapshots ``(state, buffer)`` at ``self.completed_episodes``.

        Train-while-serve: ``publisher`` (a
        :class:`~gsc_tpu.serve.fleet.WeightPublisher`) + a positive
        ``publish_interval`` publish the actor params as a versioned
        hot-swap artifact every N drained-finite episodes — a
        concurrently running serving fleet's VersionWatchers pick each
        version up between dispatches.  With the rollback guard on
        (default), what ships is the guard's VERIFIED snapshot — the
        same state a periodic checkpoint saves — so a poisoned state is
        never published (the live carry is one dispatch ahead and
        unverified).  ``Trainer(rollback=False)`` has no verified
        snapshot and falls back to the live params.  Host gather at
        checkpoint-like cadence, never on the per-episode path."""
        if getattr(self.driver, "topo_mix", None):
            # the mix fills a replica axis this path does not have —
            # silently training one topology would fake mixture coverage
            raise ValueError(
                "topo_mix needs the replica-parallel path "
                "(train_parallel / --replicas > 1); the single-env loop "
                "has no batch axis to fill with the mixture")
        if profile and self.result_dir:
            from ..utils.debug import Profiler
            with Profiler(os.path.join(self.result_dir, "profile")):
                return self.train(episodes, test_mode, verbose,
                                  profile=False, init_state=init_state,
                                  init_buffer=init_buffer,
                                  start_episode=start_episode,
                                  pipeline=pipeline,
                                  ckpt_manager=ckpt_manager,
                                  ckpt_interval=ckpt_interval,
                                  preempt=preempt, publisher=publisher,
                                  publish_interval=publish_interval)
        self.phase_timer = timer = PhaseTimer()
        hub = self.obs.hub if self.obs else None
        base = jax.random.PRNGKey(self.seed)
        steps_per_ep = self.agent_cfg.episode_steps
        plan = self.fault_plan
        guard = RollbackGuard() if self.rollback else None
        self.preempted = False
        self._last_drained = start_episode - 1
        if ckpt_interval and ckpt_manager is not None and guard is None:
            log.warning("periodic checkpoints need the rollback guard's "
                        "verified snapshots (Trainer(rollback=True)) — "
                        "--ckpt-interval is ignored this run")

        if self.ddpg.donate:
            # restored carries (orbax checkpoints, caller-held pytrees) may
            # alias each other or host-owned storage; donation needs
            # exclusively-owned device buffers — donating a restored state
            # aborts the process on the CPU backend (pending_donation_
            # check).  Re-materialize once before the first donated
            # dispatch, mirroring init()'s target-aliasing break.
            if init_state is not None:
                init_state = jax.tree_util.tree_map(jnp.copy, init_state)
            if init_buffer is not None:
                init_buffer = jax.tree_util.tree_map(jnp.copy, init_buffer)

        def make_prefetcher(from_ep):
            # traffic staged to device FROM THE PREFETCH THREAD, so the
            # host→device transfer also overlaps the running episode; the
            # topology object passes through untouched (it is the driver's
            # cached pytree — id()-keyed caches downstream rely on that)
            # stop bound covers the unconditional initial sample even when
            # the episode range is empty (the serial loop's behavior)
            pf = self.driver.prefetcher(
                from_ep, max(episodes, start_episode + 1), test_mode,
                stage=lambda topo, traffic: (topo, jax.device_put(traffic)),
                heartbeat=(self.obs.prefetcher_heartbeat()
                           if self.obs else None),
                before_episode=self._prefetch_fault_hook())
            self._live_prefetch = pf
            if self.obs:
                self.obs.attach_prefetcher(pf)
            return pf

        prefetch = make_prefetcher(start_episode) if pipeline else None
        pipeline_faults = 0
        if self.obs:
            if self.obs.watchdog is not None:
                # escalation target for the duration of the episode loop:
                # the watchdog interrupts the live prefetcher; the loop's
                # recovery path below does the restart
                self.obs.watchdog.on_escalate = self._on_watchdog_escalate
            # arm the stall monitor only while the episode loop runs —
            # compile/eval/checkpoint time is not a pipeline stall
            self.obs.resume_watchdog()

        pending = []  # dispatched episodes whose metrics are not yet synced
        # serial path drains immediately (the seed behavior); pipelined
        # drains lag one episode so the sync never gates the next dispatch
        max_pending = 1 if pipeline else 0

        def next_episode(ep):
            nonlocal prefetch, pipeline_faults, max_pending
            while prefetch is not None:
                try:
                    # blocks only when the producer thread is behind —
                    # i.e. host sampling is the true bottleneck, not the
                    # sync order
                    with phase_span("host_sample_wait", timer, hub):
                        return prefetch.get(ep)
                except RuntimeError as e:
                    # pipeline fault: producer death (surfaced error) or
                    # a watchdog-escalation interrupt.  Restart from the
                    # episode counter — staging is keyed purely by episode
                    # index, so the restarted sequence is bit-identical —
                    # or degrade pipeline->off past the fault limit.
                    pipeline_faults += 1
                    prefetch.close()
                    fault = f"{type(e).__name__}: {e}"
                    if pipeline_faults > self.pipeline_fault_limit:
                        prefetch = None
                        self._live_prefetch = None
                        max_pending = 0
                        self._recover(
                            ep, site="pipeline", action="pipeline_off",
                            fault=fault, attempt=pipeline_faults,
                            detail=f"{pipeline_faults} pipeline faults > "
                                   f"limit {self.pipeline_fault_limit}; "
                                   "serial sampling + immediate drains "
                                   "for the rest of the run")
                    else:
                        self._recover(
                            ep, site="prefetcher", action="restart",
                            fault=fault, attempt=pipeline_faults,
                            detail=f"re-staging from episode {ep}")
                        prefetch = make_prefetcher(ep)
            with phase_span("host_sample", timer, hub):
                return self.driver.episode(ep, test_mode)
        try:
            topo, traffic = next_episode(start_episode)
            env_state, obs = self.env.reset(
                jax.random.fold_in(base, 1000 + start_episode), topo,
                traffic)
            state = init_state if init_state is not None else \
                self.ddpg.init(jax.random.fold_in(base, 0), obs)
            buffer = init_buffer if init_buffer is not None else \
                self.ddpg.init_buffer(obs)
            # replay residency is static across the run (ring buffer):
            # computed once from shapes, streamed in every episode event
            replay_bytes = buffer_nbytes(buffer)
            if verbose:
                log.info(
                    "replay buffer: %.1f MiB resident%s",
                    replay_bytes / 2 ** 20,
                    " — donated, updated in place each episode"
                    if self.ddpg.donate else
                    " — copied each episode (donate=False)")

            # device-cost ledger capture (obs.perf): AOT-lower the watched
            # entry points ONCE, here at compile time — before any dispatch
            # and before donation can consume a carry (lowering never
            # executes the program; see _ledger_fn for which executable is
            # mined).  The steady-state variant (learn=True) is the one
            # the roofline table describes.
            gs0 = np.int32(start_episode * steps_per_ep)
            if pipeline:
                fn, pre = self._ledger_fn(self.ddpg, "episode_step")
                self._capture_costs({"episode_step": (
                    fn, (*pre, state, buffer, env_state, obs, topo,
                         traffic, gs0), {"learn": True})})
            else:
                r_fn, r_pre = self._ledger_fn(self.ddpg, "rollout_episode")
                l_fn, l_pre = self._ledger_fn(self.ddpg, "learn_burst")
                self._capture_costs({
                    "rollout_episode": (
                        r_fn, (*r_pre, state, buffer, env_state, obs,
                               topo, traffic, gs0), {}),
                    "learn_burst": (l_fn, (*l_pre, state, buffer), {}),
                })

            if guard is not None:
                # rollback target for a violation before any episode has
                # been verified (the fresh/restored state is finite)
                guard.init(start_episode - 1, state, buffer)

            start = time.time()

            def drain_one():
                """Drain the oldest pending episode; on a finite verdict
                promote snapshots + periodic-checkpoint, on a violation
                roll back and drop the in-flight descendants."""
                nonlocal state, buffer
                entry = pending.pop(0)
                k = entry[0]
                finite = self._drain(entry, start, start_episode, verbose,
                                     timer)
                if finite:
                    self._last_drained = max(self._last_drained, k)
                    if guard is not None:
                        guard.promote(k, state, buffer,
                                      pending_empty=not pending)
                        if (ckpt_manager is not None and ckpt_interval
                                and (k + 1 - start_episode) % ckpt_interval
                                == 0 and guard.last_good is not None
                                and guard.last_good[0] == k):
                            # the promoted snapshot IS the verified state
                            # after episode k — exactly what a resumable
                            # checkpoint must contain (the live carries
                            # may already be an episode ahead)
                            _, g_state, g_buffer = guard.last_good
                            ckpt_manager.save(g_state, g_buffer,
                                              episode=k + 1)
                    if (publisher is not None and publish_interval
                            and (k + 1 - start_episode)
                            % publish_interval == 0):
                        # hot-swap publish: with the guard on, ship the
                        # VERIFIED snapshot the promote above just
                        # landed (state after episode k) — the live
                        # carry is up to one dispatch ahead and its
                        # finite flag has NOT drained yet, so publishing
                        # it could ship a poisoned state one episode
                        # before rollback catches it (the periodic
                        # checkpoint above refuses that for the same
                        # reason).  Rollback disabled = no verified
                        # snapshot exists; fall back to the live params
                        # (this drain's flag was finite, the next
                        # dispatch's is anyone's guess — documented).
                        src = None
                        if guard is not None:
                            if guard.last_good is not None \
                                    and guard.last_good[0] == k:
                                src = guard.last_good[1].actor_params
                        else:
                            src = state.actor_params
                        if src is not None:
                            # verified=True: both branches above ship a
                            # finite-verified state (promoted snapshot,
                            # or the live params whose flag just drained
                            # finite) — skip the publisher's own host
                            # scan
                            publisher.publish(jax.device_get(src),
                                              meta={"episode": k + 1},
                                              verified=True)
                    return
                if guard is None:
                    self._recover(
                        k, site="learner_state", action="detected",
                        fault="non_finite_state",
                        detail="rollback disabled (Trainer(rollback="
                               "False)) — continuing with the poisoned "
                               "state")
                    self._last_drained = max(self._last_drained, k)
                    return
                dropped = [e[0] for e in pending]
                pending.clear()
                tag, state, buffer = guard.restore()
                self._recover(
                    k, site="learner_state", action="rollback",
                    fault="non_finite_state",
                    detail=f"restored snapshot of episode {tag}; skipped "
                           f"poisoned episode {k}"
                           + (f"; dropped in-flight {dropped}"
                              if dropped else ""))

            for ep in range(start_episode, episodes):
                if preempt is not None and preempt.triggered:
                    self.preempted = True
                    self._recover(
                        ep, site="run", action="preempt_snapshot",
                        fault=preempt.signame,
                        detail=f"stopping before episode {ep}; in-flight "
                               "episodes drain, then the caller "
                               "checkpoints")
                    break
                if ep > start_episode:
                    topo, traffic = next_episode(ep)
                    env_state, obs = self.env.reset(
                        jax.random.fold_in(base, 1000 + ep), topo, traffic)
                global_step = ep * steps_per_ep
                end_step = global_step + steps_per_ep - 1
                learn = (end_step
                         >= self.agent_cfg.nb_steps_warmup_critic - 1)
                if guard is not None:
                    # candidate snapshot at the dispatch boundary: the
                    # state after episode ep-1, not yet verified (its
                    # finite flag drains one episode later under the
                    # pipeline) — promote() gates it.  Taken BEFORE the
                    # fault injection below so an injected poison can
                    # never be promoted, and copied so the dispatch's
                    # donation cannot invalidate it.
                    guard.stage(ep - 1, state, buffer)
                if plan is not None:
                    spec = plan.fire("nan_grads", ep)
                    if spec is not None:
                        # the effect of a NaN gradient update: the state
                        # entering this episode is poisoned; the
                        # on-device flag catches it at this episode's
                        # drain
                        state = state.replace(
                            actor_params=poison_tree(state.actor_params))
                (state, buffer, env_state, obs, stats,
                 learn_metrics) = self._dispatch_with_retry(
                    ep, pipeline, state, buffer, env_state, obs, topo,
                    traffic, global_step, learn, timer, hub)
                if self.obs:
                    self.obs.episode_dispatched(ep)
                # the retained arrays (stats, learn metrics, the truncation
                # scalar, and the episode-final sim state the obs/invariant
                # layer reads) are plain kernel outputs — never donated
                # (the NEXT episode's env_state comes from a fresh
                # env.reset, not this one), so deferring their sync is
                # safe under buffer donation
                pending.append((ep, end_step, stats, learn_metrics,
                                env_state.sim.truncated_arrivals,
                                env_state.sim, topo, replay_bytes))
                while len(pending) > max_pending:
                    drain_one()
            while pending:
                # happy-path tail drain stays INSIDE the try: an async
                # device fault surfacing at the final episode's sync must
                # raise like the serial loop would, not be downgraded
                drain_one()
        finally:
            if self.obs:
                # disarm BEFORE the best-effort teardown drains — a fault
                # recovery path must not also spray stall events
                self.obs.pause_watchdog()
                if self.obs.watchdog is not None:
                    self.obs.watchdog.on_escalate = None
            self._live_prefetch = None
            # only nonempty when an exception is already propagating:
            # flush completed episodes' rows into rewards.csv exactly as
            # the serial loop would have written them before the fault.
            # Best effort — a drain that itself fails (device in a bad
            # state) must not mask the original exception.
            while pending:
                entry = pending.pop(0)
                try:
                    self._drain(entry, start, start_episode, verbose,
                                timer)
                except Exception:
                    log.warning("dropping metrics of episode %d: drain "
                                "failed after a faulted dispatch", entry[0])
                    break
            if prefetch is not None:
                prefetch.close()
        self.completed_episodes = self._last_drained + 1
        # measured wall -> ledger AFTER the loop (the deferred-drain
        # totals), so MFU/roofline derive from timings the dispatch path
        # already paid for — zero new host syncs
        self._note_cost_timings(
            timer, "episode_step" if pipeline else None)
        if plan is not None:
            # shared end-of-run check (FaultPlan.warn_unfired): a
            # mis-keyed plan must be loud on EVERY training path, with
            # the same structured event
            plan.warn_unfired(self.obs.hub if self.obs else None)
        if verbose:
            log.info("pipeline phase timings: %s", timer.summary())
        self.rewards_writer.close()
        if self.tb:
            self.tb.close()
        return state, buffer

    def train_parallel(self, episodes: int, num_replicas: int,
                       chunk: int = 50, verbose: bool = False,
                       device_traffic: bool = True, profile: bool = False,
                       init_state: Optional[DDPGState] = None,
                       init_buffers=None, start_episode: int = 0,
                       ckpt_manager=None, ckpt_interval: int = 0,
                       preempt=None, plan=None, publisher=None,
                       publish_interval: int = 0, curriculum=None):
        """Replica-parallel training: B vmapped env replicas per episode on
        the scheduled topology, chunked rollouts + end-of-episode learn
        burst (the bench/learning-curve path), logged through the same
        rewards.csv/history machinery as ``train``.  Per-episode traffic is
        sampled ON DEVICE by default (one DeviceTraffic sampler per
        distinct scheduled topology).  Returns (state, buffers).

        The reference has no analogue (one process, one env); evaluation
        and checkpointing consume the resulting learner state exactly like
        the single-env path's.

        ``plan`` (a ``parallel.ShardingPlan``, ``cli train --mesh``):
        replicas/replay/traffic shard over the plan's dp x mp device grid
        and the learner state lives in the plan's partition-rule layout
        between dispatches (ParallelDDPG's sharded dispatch owns the
        placement — this loop drives it unchanged).  Checkpoints are
        mesh-shape-AGNOSTIC: every save below gathers the carries to host
        layout through the plan's gather fns first (orbax 0.7.0 on this
        box cannot restore sharded layouts portably — host arrays are the
        format every future mesh can reshard from), and the returned
        (state, buffers) are host-gathered for the same reason, so the
        caller's final checkpoint + evaluation never see mesh residency.
        Elastic resume = restore those host arrays under a DIFFERENT
        plan: the first dispatch reshards them onto whatever mesh the
        resuming process built.  Under the ``tp`` book the state is
        RESIDENT-sharded through the compiled program (no entry/exit
        layout moves at all) — this loop still never touches mesh
        residency between dispatches: the ONLY host gathers are the
        save boundaries and the final return below, where
        ``gather_state`` assembles the sharded leaves directly.

        On-device scenario factory (``--topo-mix factory:...``): when
        the driver carries a :class:`~gsc_tpu.topology.factory.
        FactorySpec`, every episode SAMPLES a fresh per-replica
        (topology, traffic, fault plan) inside one jitted
        ``factory_sample`` call — the host-staged MixPlan products are
        replaced by device tensors, the ``scenario_regen`` phase
        collapses to dispatch-enqueue time, and nothing retraces (the
        bucket's shapes are static).  Batch composition is steered by
        the TD curriculum (:mod:`gsc_tpu.env.curriculum`, ``curriculum``
        = a ``CurriculumConfig``): each drained episode's per-family
        |TD| segment sums (the learn ledger's existing signal) update
        per-family EWMAs whose softmax — floored with a uniform mix —
        becomes the next episode's family-sampling weights
        (``curriculum_weight{family=}`` gauges + ``curriculum`` events).
        Without a learn ledger the weights stay uniform.

        Train-while-serve: ``publisher`` + a positive
        ``publish_interval`` publish the actor params every N episodes,
        exactly like :meth:`train` — except this path's carries are
        replica/mesh-sharded, so what ships is the HOST-GATHERED state
        (the plan's gather fns under ``--mesh``), finite-verified
        host-side first (this path has no rollback guard; a poisoned
        state skips the publish loudly instead of reaching the fleet).

        Resilience on this path: preemption stop + periodic checkpoints
        (finite-verified host-side).  Under a fault plan the harness
        additionally wires ``nan_grads`` (the state entering the keyed
        episode is poisoned) plus a host-side finite verify after EVERY
        episode, backed by a ``RollbackGuard`` last-verified snapshot
        when ``Trainer(rollback=True)`` — the replica loop drains
        synchronously, so the carries after an episode ARE the verified
        state and snapshots promote directly.  Without a plan none of
        this runs: the production path is byte-identical to before."""
        if profile and self.result_dir:
            from ..utils.debug import Profiler
            with Profiler(os.path.join(self.result_dir, "profile")):
                return self.train_parallel(episodes, num_replicas, chunk,
                                           verbose, device_traffic,
                                           profile=False,
                                           init_state=init_state,
                                           init_buffers=init_buffers,
                                           start_episode=start_episode,
                                           ckpt_manager=ckpt_manager,
                                           ckpt_interval=ckpt_interval,
                                           preempt=preempt, plan=plan,
                                           publisher=publisher,
                                           publish_interval=publish_interval,
                                           curriculum=curriculum)
        from ..parallel import ParallelDDPG
        from ..parallel.harness import run_chunked_episodes
        from ..sim.traffic_device import DeviceTraffic

        steps_per_ep = self.agent_cfg.episode_steps
        if steps_per_ep % chunk != 0:
            # never silently upgrade to a single full-episode scan — that
            # is exactly the call shape the chunking exists to avoid
            raise ValueError(
                f"chunk ({chunk}) must divide episode_steps "
                f"({steps_per_ep})")
        # mixed-topology batches (EpisodeDriver(topo_mix=...)): the B axis
        # carries a round-robin of the schedule's networks + registry
        # scenarios instead of one topology — per_replica_topology threads
        # the stacked [B] topology pytree through the vmapped dispatch, so
        # topology diversity fills the batch instead of costing wall-clock
        # episodes, and a "schedule switch" never recompiles (the switch
        # IS the per-replica topology tensor)
        # on-device scenario factory (topology.factory): the driver's
        # factory spec replaces the host MixPlan wholesale — scenarios
        # are device tensors sampled per episode, steered by the TD
        # curriculum below
        factory = (self.driver.scenario_factory
                   if getattr(self.driver, "factory_spec", None)
                   is not None else None)
        if factory is not None and not device_traffic:
            raise ValueError(
                "the scenario factory IS on-device sampling — "
                "device_traffic=False has no host path to fall back to "
                "(use a registry --topo-mix for host-generated traffic)")
        mix_plan = (self.driver.mix_plan(num_replicas)
                    if getattr(self.driver, "topo_mix", None)
                    and factory is None else None)
        if mix_plan is not None:
            from ..topology.scenarios import (mix_device_samplers,
                                              sample_mix_device)
        curr = None
        if factory is not None:
            from ..env.curriculum import Curriculum, CurriculumConfig
            curr = Curriculum(factory.family_names,
                              curriculum or CurriculumConfig())
        pddpg = ParallelDDPG(self.env, self.agent_cfg,
                             num_replicas=num_replicas, donate=True,
                             gnn_impl=self.ddpg.actor.gnn_impl, plan=plan,
                             per_replica_topology=(mix_plan is not None
                                                   or factory is not None),
                             learn_ledger=self.ddpg.learn_ledger)
        # learn-ledger segment names (topo_id -> name) for the harness's
        # per-episode learn_signal emit; None without a ledger
        seg_names = (self.learn_obs.segment_names
                     if self.learn_obs is not None else None)

        def to_host(state, buffers):
            """Carries in the mesh-shape-agnostic host layout checkpoints
            are written in (and the caller receives): the plan's per-leaf
            gather fns for the learner state, a plain device_get for the
            replica shards.  Without a plan this is the identity — the
            historic path hands orbax the live device arrays."""
            if plan is None:
                return state, buffers
            return plan.gather_state(state), jax.device_get(buffers)
        base = jax.random.PRNGKey(self.seed)
        # restored carries must be re-materialized before donation — see
        # train(): donating orbax-restored (host-owned / aliased) buffers
        # aborts the process
        if init_state is not None:
            init_state = jax.tree_util.tree_map(jnp.copy, init_state)
        if init_buffers is not None:
            init_buffers = jax.tree_util.tree_map(jnp.copy, init_buffers)

        topo0, traffic0 = self.driver.episode(0, False)
        _, one_obs = self.env.reset(jax.random.fold_in(base, 1000), topo0,
                                    traffic0)
        state = init_state if init_state is not None else \
            pddpg.init(jax.random.fold_in(base, 0), one_obs)
        buffers = init_buffers if init_buffers is not None else \
            pddpg.init_buffers(one_obs)

        chaos = self.fault_plan
        guard = None
        if chaos is not None and self.rollback:
            # chaos-only rollback target (tree_copy'd snapshots — the
            # donating dispatch can never invalidate them)
            guard = RollbackGuard()
            guard.init(start_episode - 1, state, buffers)

        # one on-device sampler per scheduled topology (the scheduler
        # cycles training_network_files every `period` episodes); mixed
        # runs instead build one sampler per MIX ENTRY (each with its
        # scenario's traffic shape / fault tables) and interleave the
        # per-entry draws back into replica order
        samplers = {}
        mix_samplers = None

        def episode_traffic(ep, topo):
            nonlocal mix_samplers
            if mix_plan is not None:
                if not device_traffic:
                    return self.driver.mix_traffic(ep, mix_plan)
                if mix_samplers is None:
                    mix_samplers = mix_device_samplers(
                        mix_plan, self.env.sim_cfg, self.env.service,
                        steps_per_ep, default_trace=self.driver.trace)
                return sample_mix_device(
                    mix_plan, mix_samplers,
                    jax.random.fold_in(base, 2000 + ep))
            if not device_traffic:
                stacked = [self.driver.traffic_for(
                    ep, topo, seed=self.driver.base_seed + 1000 * ep + r)
                    for r in range(num_replicas)]
                return jax.tree_util.tree_map(
                    lambda *xs: jax.numpy.stack(xs), *stacked)
            # key by the topology OBJECT the episode actually uses — the
            # driver owns the schedule; re-deriving its index here would
            # duplicate that invariant
            if id(topo) not in samplers:
                samplers[id(topo)] = DeviceTraffic(
                    self.env.sim_cfg, self.env.service, topo, steps_per_ep,
                    trace=self.driver.trace, capacity=self.driver.capacity)
            return samplers[id(topo)].sample_batch(
                jax.random.fold_in(base, 2000 + ep), num_replicas)

        self.phase_timer = timer = PhaseTimer()
        hub = self.obs.hub if self.obs else None
        self.preempted = False
        self._last_drained = start_episode - 1
        if self.obs:
            self.obs.resume_watchdog()

        def _curriculum_hook(_i, _ret, _succ, metrics):
            """Harness ``on_episode`` callback (factory mode): fold the
            drained learn signal's per-family |TD| segments into the
            curriculum EWMAs.  The harness drain already synced these
            values — pure host arithmetic, never a device wait.  No
            ledger (``--no-learn-obs``) => no signal => the weights stay
            uniform (documented)."""
            sig = (metrics or {}).get("learn_signal") \
                if isinstance(metrics, dict) else None
            if sig is not None:
                curr.fold_td(np.asarray(sig["td_abs_sum"]),
                             np.asarray(sig["td_count"]))

        start = time.time()
        try:
            # the scheduler may swap topologies mid-run, so drive the
            # harness one episode at a time with that episode's topology —
            # passing the GLOBAL step offset so the agent's warmup schedule
            # sees one continuous run (and a resumed run continues it
            # exactly)
            for ep in range(start_episode, episodes):
                if preempt is not None and preempt.triggered:
                    self.preempted = True
                    self._recover(
                        ep, site="run", action="preempt_snapshot",
                        fault=preempt.signame,
                        detail=f"stopping before episode {ep}; the caller "
                               "checkpoints the drained state")
                    break
                # the scenario_regen phase measures what producing this
                # episode's (topology, traffic) costs the HOST: the full
                # Python regen wall on host-traffic paths, dispatch-
                # enqueue time on device-sampling paths — the cost the
                # factory deletes, measured instead of asserted
                # (SCEN_r01 banks the before/after)
                with phase_span("scenario_regen", timer, hub):
                    if factory is not None:
                        # fresh per-replica scenarios, entirely on
                        # device: family weights from the curriculum
                        # (tiny [K] host vector — data, never a compile
                        # axis), keys by episode index like the device
                        # traffic samplers
                        probs = jax.numpy.asarray(curr.weights(),
                                                  jax.numpy.float32)
                        topo, traffic = factory.sample_batch(
                            jax.random.fold_in(base, 2000 + ep), probs,
                            num_replicas)
                    else:
                        # mixed mode: the stacked topology is the SAME
                        # pytree object every episode (driver memo), so
                        # the device placement memo and the compiled
                        # program both hit — the whole mixture trains
                        # with exactly one trace
                        topo = (mix_plan.topo if mix_plan is not None
                                else self.driver.topology_for(ep))
                        traffic = episode_traffic(ep, topo)
                if ep == start_episode and self.obs is not None \
                        and getattr(self.obs, "perf", None) is not None:
                    # cost-ledger capture for the replica path: shapes-only
                    # reset via eval_shape (no device work), then AOT-lower
                    # the fused chunk kernel's steady-state variant.  Under
                    # a sharding plan this lowers the PLAIN jit — no
                    # explicit in_/out_shardings, the carving-comparable
                    # number (the traced body still carries the plan's
                    # with_sharding_constraints, so under `tp` even this
                    # program partitions — the _sharded capture below is
                    # the one that mines the dispatched layout) — and
                    # because
                    # the sharded dispatch jits its own copy, that capture
                    # trace would read as a spurious chunk_step retrace in
                    # the sentinel stream: pause the monitor for exactly
                    # that case (meshless captures share the dispatch's
                    # trace cache, so they stay un-paused and count once,
                    # same reasoning as bench.py's --perf path).
                    mon = self.obs.compile_monitor
                    paused = plan is not None and mon is not None
                    if paused:
                        mon.stop()
                    try:
                        pcls = type(pddpg)
                        es_s, obs_s = pcls.reset_all.eval_shape(
                            pddpg, jax.random.PRNGKey(0), topo, traffic)
                        c_fn, c_pre = self._ledger_fn(pddpg, "chunk_step")
                        l_fn, l_pre = self._ledger_fn(pddpg, "learn_burst")
                        self._capture_costs({
                            "chunk_step": (
                                c_fn,
                                (*c_pre, state, buffers, es_s, obs_s,
                                 topo, traffic,
                                 np.int32(ep * steps_per_ep)),
                                {"num_steps": chunk, "learn": True}),
                            "learn_burst": (
                                l_fn, (*l_pre, state, buffers), {}),
                        })
                        if factory is not None:
                            # the factory-inclusive program: the jitted
                            # scenario sampler is episode device work
                            # too — mine its HLO next to chunk_step.
                            # The AOT lower shares the sampler jit's
                            # trace cache (same jit object, same
                            # shapes), so the capture never shows as a
                            # spurious factory_sample retrace.
                            self._capture_costs({
                                "factory_sample": (
                                    factory.lowerable(num_replicas),
                                    (jax.random.PRNGKey(0), probs), {}),
                            })
                        if plan is not None:
                            # ALSO capture the PARTITIONED executable the
                            # sharded dispatch actually runs: its HLO
                            # carries the collective ops (all-reduce
                            # count/bytes) the plain capture above cannot
                            # show — the machine-read half of the
                            # tp-vs-sharded interconnect claim.  One
                            # extra AOT compile at startup (--no-perf
                            # skips it); under the multi-device CPU
                            # cache wart the lowering must run with the
                            # persistent cache disabled, same guard as
                            # the dispatch compiles.  The sharded jit
                            # takes statics positionally (in_shardings
                            # rejects kwargs).
                            from ..parallel.partition import \
                                no_persistent_compile_cache
                            s_fn = pddpg.sharded_lowerable("chunk_step",
                                                           state)
                            with no_persistent_compile_cache(plan.mesh):
                                self._capture_costs({
                                    "chunk_step_sharded": (
                                        s_fn,
                                        (state, buffers, es_s, obs_s,
                                         topo, traffic,
                                         np.int32(ep * steps_per_ep),
                                         chunk, True), {}),
                                })
                    except Exception as e:  # noqa: BLE001 - never fatal
                        log.warning("cost-ledger capture skipped on the "
                                    "replica path: %s", e)
                    finally:
                        if paused:
                            mon.start()
                if chaos is not None:
                    spec = chaos.fire("nan_grads", ep)
                    if spec is not None:
                        # the effect of a NaN gradient update: the state
                        # entering this episode is poisoned; the chaos
                        # verify below catches it at the episode's end
                        state = state.replace(
                            actor_params=poison_tree(state.actor_params))
                if self.obs:
                    self.obs.episode_dispatched(ep)
                state, buffers, rets, succ, final = run_chunked_episodes(
                    pddpg, topo, lambda _: traffic, state, buffers,
                    1, steps_per_ep, chunk, self.seed + ep,
                    step_offset=ep * steps_per_ep, hub=hub, timer=timer,
                    topo_names=(mix_plan.names if mix_plan is not None
                                else None),
                    learn_names=seg_names,
                    on_episode=(_curriculum_hook if curr is not None
                                else None))
                if curr is not None:
                    # next episode's family weights, from THIS episode's
                    # drained TD segments (the hook above updated the
                    # EWMAs) — gauges + one curriculum event per episode
                    curr.emit_weights(hub, ep)
                if chaos is not None:
                    # chaos-only episode-end verify (one host gather per
                    # episode, NEVER on the production path): the replica
                    # harness drains synchronously, so the carries here
                    # are exactly the state after episode ep
                    if self._finite_host(jax.device_get(state)):
                        if guard is not None:
                            guard.promote(ep, state, buffers,
                                          pending_empty=True)
                    elif guard is not None:
                        tag, state, buffers = guard.restore()
                        self._recover(
                            ep, site="learner_state", action="rollback",
                            fault="non_finite_state",
                            detail=f"restored snapshot of episode {tag}; "
                                   f"dropped poisoned episode {ep}")
                    else:
                        self._recover(
                            ep, site="learner_state", action="detected",
                            fault="non_finite_state",
                            detail="rollback disabled (Trainer(rollback="
                                   "False)) — continuing with the "
                                   "poisoned state")
                sps = ((ep - start_episode + 1) * steps_per_ep
                       * num_replicas / (time.time() - start))
                row = {"episodic_return": rets[0],
                       "mean_succ_ratio": succ[0],
                       "final_succ_ratio": final[0], "episode": ep,
                       "sps": sps}
                self.history.append(row)
                self.rewards_writer.write(rets[0])
                if self.tb:
                    gs = (ep + 1) * steps_per_ep
                    self.tb.add_scalar("charts/episodic_return", rets[0], gs)
                    self.tb.add_scalar("charts/SPS", sps, gs)
                if verbose:
                    log.info("episode=%d return=%.3f succ=%.3f sps=%.1f",
                             ep, rets[0], succ[0], sps)
                if self.obs:
                    extra = {"replicas": num_replicas}
                    if mix_plan is None and factory is None:
                        # homogeneous replica batches: one network per
                        # episode — same stamp as the serial drain (the
                        # harness's per-replica names cover mixes;
                        # factory episodes attribute per FAMILY through
                        # the learn ledger's topo_id segments, not a
                        # schedule name)
                        extra = self._topology_extra(ep, rets[0],
                                                     extra=extra)
                    self.obs.episode_end(
                        episode=ep, global_step=(ep + 1) * steps_per_ep - 1,
                        metrics={k: v for k, v in row.items()
                                 if k not in ("episode", "sps")},
                        sps=sps, phases=timer.summary(),
                        replay_bytes=buffer_nbytes(buffers),
                        extra=extra)
                self._last_drained = ep
                if (publisher is not None and publish_interval
                        and (ep + 1 - start_episode) % publish_interval
                        == 0):
                    # hot-swap publish from the replica path (ROADMAP
                    # item 3's last leftover): only the ACTOR subtree
                    # ships, so gather exactly that — device_get
                    # assembles sharded leaves to host arrays (the same
                    # per-leaf move the plan's gather fns perform;
                    # pulling the whole state would move ~5x the bytes,
                    # and critic/targets/moments never serve).  With no
                    # rollback guard here, finite-verify before
                    # anything reaches the fleet.  Host gather at
                    # publish cadence only, never per episode.
                    params = jax.device_get(state.actor_params)
                    if self._finite_host(params):
                        publisher.publish(params, meta={"episode": ep + 1},
                                          verified=True)
                    else:
                        self._recover(
                            ep, site="learner_state", action="detected",
                            fault="non_finite_state",
                            detail="replica path has no rollback guard — "
                                   "hot-swap publish skipped so a "
                                   "poisoned state never reaches the "
                                   "serving fleet")
                if (ckpt_manager is not None and ckpt_interval
                        and (ep + 1 - start_episode) % ckpt_interval == 0):
                    # the replica harness drains synchronously, so the
                    # live carries ARE the state after episode ep — but
                    # with no rollback guard on this path the state must
                    # be verified HERE, or a NaN-poisoned run would
                    # checksum garbage into the last-good resume target.
                    # One host-side scan at checkpoint cadence (the save
                    # needs these leaves on host anyway — under a plan
                    # the gather IS the mesh-agnostic checkpoint layout).
                    h_state, h_buffers = to_host(state, buffers)
                    if self._finite_host(h_state):
                        ckpt_manager.save(h_state, h_buffers,
                                          episode=ep + 1)
                    else:
                        self._recover(
                            ep, site="learner_state", action="detected",
                            fault="non_finite_state",
                            detail="replica path has no rollback guard — "
                                   "checkpoint skipped so the last-good "
                                   "pointer keeps the previous verified "
                                   "state")
        finally:
            if self.obs:
                self.obs.pause_watchdog()
        self.completed_episodes = self._last_drained + 1
        if chaos is not None:
            chaos.warn_unfired(hub)
        self._note_cost_timings(timer, "chunk_step")
        self.rewards_writer.close()
        if self.tb:
            self.tb.close()
        # host layout on the way out (identity without a plan): the
        # caller's final checkpoint, the preemption snapshot and the
        # greedy evaluation must never depend on this run's mesh carving
        return to_host(state, buffers)

    def train_async(self, episodes: int, num_replicas: int,
                    chunk: int = 50, actor_threads: int = 2,
                    verbose: bool = False, device_traffic: bool = True,
                    profile: bool = False,
                    init_state: Optional[DDPGState] = None,
                    init_buffers=None, start_episode: int = 0,
                    ckpt_manager=None, ckpt_interval: int = 0,
                    preempt=None, plan=None, publisher=None,
                    publish_bursts: int = 1, curriculum=None,
                    max_staleness: int = 0, learn_ratio: float = 1.0,
                    throttle_s: float = 0.0):
        """Decoupled actor/learner training (``cli train --async``):
        ``actor_threads`` rollout threads run the jitted replica rollout
        continuously and ship device-resident transition blocks into the
        shared replay ring, while THIS thread — the learner — ingests
        them via one jitted ``replay_ingest`` per block, runs learn
        bursts back-to-back under its ``learn_ratio`` pacing, and
        publishes actor weights every ``publish_bursts`` bursts through
        a :class:`~gsc_tpu.serve.fleet.WeightPublisher` the actors
        subscribe to in-process (see :mod:`gsc_tpu.parallel.async_rl`
        for the full architecture + staleness-bounding contract).

        Scenario production (scheduled topology + DeviceTraffic,
        registry ``--topo-mix``, or the on-device factory with the TD
        curriculum) matches :meth:`train_parallel` episode for episode —
        scenarios are keyed by GLOBAL episode index, so what an episode
        trains on does not depend on which actor thread ran it.

        Mesh composition: ``plan`` (``--mesh``) now composes — the replay
        ring lives dp-sharded on the learner mesh (``plan.ring_sharding``)
        and ``run_async`` pre-builds the plan-bound dispatch plus the
        AOT-compiled per-shard donated ingest BEFORE any actor thread
        starts, under one run-wide compile-cache guard (the lazy-build
        race that used to force a refusal is dead code).  Learn-bursts
        run under the full pjit plan (tp rulebooks compose), and each
        publish gathers params to host once for both the actor watchers
        and the serving fleet.  Tp-only meshes (dp=1 with >1 devices)
        are still refused — the ring has no dp axis to shard over.

        Resilience: the fleet is SUPERVISED — a dead actor thread
        restarts from its episode counter within
        ``AsyncConfig.restart_budget``, then the fleet degrades to fewer
        actors (never hangs).  Under ``--fault-plan`` the async sites
        (``actor_die@a<N>:<ep>``, ``ring_poison``, ``publish_corrupt@
        v<N>``, ``watcher_stall``, ``learner_transient@<burst>``) fire
        inside :func:`~gsc_tpu.parallel.async_rl.run_async`, the learner
        finite-gates every popped block (poison quarantine) and keeps a
        ``RollbackGuard`` last-verified snapshot keyed by the burst-level
        ``state_finite`` flag; every recovery flows through
        ``RunObserver.recovery``.  Without a plan none of that costs
        anything — the fault-free path is byte-identical.

        One documented limit remains: bit-exact learning curves vs the
        sync control — actors act on K-burst-old weights by design;
        equivalence is BANDED (bench_diff curve bands at matched
        env-step + gradient-step budgets, tools/async_bench.py), never a
        digest.

        ``throttle_s`` artificially delays each burst (test/chaos knob
        for forcing backpressure); ``max_staleness`` bounds how many
        produced-but-uningested env steps the actors may run ahead
        (0 = one episode per actor).  Returns (state, buffers); the
        run's measured accounting (learner idle fraction, policy-lag
        extrema, produced==ingested proof) lands in
        ``self.async_info``."""
        if plan is not None:
            # dp-sharded replay needs a dp axis; tp-only grids refuse
            # with the recarve instructions (partition.py)
            plan.assert_async_capable()
        if profile and self.result_dir:
            from ..utils.debug import Profiler
            with Profiler(os.path.join(self.result_dir, "profile")):
                return self.train_async(
                    episodes, num_replicas, chunk,
                    actor_threads=actor_threads, verbose=verbose,
                    device_traffic=device_traffic, profile=False,
                    init_state=init_state, init_buffers=init_buffers,
                    start_episode=start_episode,
                    ckpt_manager=ckpt_manager,
                    ckpt_interval=ckpt_interval, preempt=preempt,
                    plan=plan, publisher=publisher,
                    publish_bursts=publish_bursts,
                    curriculum=curriculum, max_staleness=max_staleness,
                    learn_ratio=learn_ratio, throttle_s=throttle_s)
        from ..parallel import ParallelDDPG
        from ..parallel.async_rl import AsyncConfig, run_async
        from ..sim.traffic_device import DeviceTraffic
        from .buffer import buffer_fill_frac

        steps_per_ep = self.agent_cfg.episode_steps
        if steps_per_ep % chunk != 0:
            raise ValueError(
                f"chunk ({chunk}) must divide episode_steps "
                f"({steps_per_ep})")
        factory = (self.driver.scenario_factory
                   if getattr(self.driver, "factory_spec", None)
                   is not None else None)
        if factory is not None and not device_traffic:
            raise ValueError(
                "the scenario factory IS on-device sampling — "
                "device_traffic=False has no host path to fall back to "
                "(use a registry --topo-mix for host-generated traffic)")
        mix_plan = (self.driver.mix_plan(num_replicas)
                    if getattr(self.driver, "topo_mix", None)
                    and factory is None else None)
        if mix_plan is not None:
            from ..topology.scenarios import (mix_device_samplers,
                                              sample_mix_device)
        curr = None
        if factory is not None:
            from ..env.curriculum import Curriculum, CurriculumConfig
            curr = Curriculum(factory.family_names,
                              curriculum or CurriculumConfig())
        # donate=False is load-bearing: actors hand their scratch blocks
        # to the learner BY REFERENCE, so rollout outputs must be fresh
        # arrays, never donated-in-place ones another thread still reads.
        # The one donated call on this path is replay_ingest, whose ring
        # the learner thread owns exclusively (async_rl module docs).
        pddpg = ParallelDDPG(self.env, self.agent_cfg,
                             num_replicas=num_replicas, donate=False,
                             gnn_impl=self.ddpg.actor.gnn_impl,
                             per_replica_topology=(mix_plan is not None
                                                   or factory is not None),
                             plan=plan,
                             learn_ledger=self.ddpg.learn_ledger)
        seg_names = (self.learn_obs.segment_names
                     if self.learn_obs is not None else None)
        base = jax.random.PRNGKey(self.seed)
        # restored carries must be re-materialized before donation —
        # replay_ingest donates the ring, and donating orbax-restored
        # (host-owned / aliased) leaves aborts the process (see train())
        if init_state is not None:
            init_state = jax.tree_util.tree_map(jnp.copy, init_state)
        if init_buffers is not None:
            init_buffers = jax.tree_util.tree_map(jnp.copy, init_buffers)

        topo0, traffic0 = self.driver.episode(0, False)
        _, one_obs = self.env.reset(jax.random.fold_in(base, 1000), topo0,
                                    traffic0)
        state = init_state if init_state is not None else \
            pddpg.init(jax.random.fold_in(base, 0), one_obs)
        buffers = init_buffers if init_buffers is not None else \
            pddpg.init_buffers(one_obs)

        samplers = {}
        mix_samplers = None

        def episode_traffic(ep, topo):
            nonlocal mix_samplers
            if mix_plan is not None:
                if not device_traffic:
                    return self.driver.mix_traffic(ep, mix_plan)
                if mix_samplers is None:
                    mix_samplers = mix_device_samplers(
                        mix_plan, self.env.sim_cfg, self.env.service,
                        steps_per_ep, default_trace=self.driver.trace)
                return sample_mix_device(
                    mix_plan, mix_samplers,
                    jax.random.fold_in(base, 2000 + ep))
            if not device_traffic:
                stacked = [self.driver.traffic_for(
                    ep, topo, seed=self.driver.base_seed + 1000 * ep + r)
                    for r in range(num_replicas)]
                return jax.tree_util.tree_map(
                    lambda *xs: jax.numpy.stack(xs), *stacked)
            if id(topo) not in samplers:
                samplers[id(topo)] = DeviceTraffic(
                    self.env.sim_cfg, self.env.service, topo, steps_per_ep,
                    trace=self.driver.trace, capacity=self.driver.capacity)
            return samplers[id(topo)].sample_batch(
                jax.random.fold_in(base, 2000 + ep), num_replicas)

        def scenario_fn(ep):
            # called from actor threads under async_rl's scenario lock;
            # keyed by GLOBAL episode index exactly like train_parallel,
            # so the scenario stream is thread-schedule-independent
            with phase_span("scenario_regen", timer, hub):
                if factory is not None:
                    probs = jax.numpy.asarray(curr.weights(),
                                              jax.numpy.float32)
                    return factory.sample_batch(
                        jax.random.fold_in(base, 2000 + ep), probs,
                        num_replicas)
                topo = (mix_plan.topo if mix_plan is not None
                        else self.driver.topology_for(ep))
                return topo, episode_traffic(ep, topo)

        self.phase_timer = timer = PhaseTimer()
        hub = self.obs.hub if self.obs else None
        self.preempted = False
        self._last_drained = start_episode - 1
        if self.obs:
            self.obs.resume_watchdog()
            # fleet watchdog coverage: every actor thread + the learner
            # register their own heartbeats (run_async beats them per
            # chunk / per loop pass), so a stall event names the wedged
            # thread and the phase it is stuck in — blocked_put vs
            # dispatch vs adopt — instead of an anonymous quiet episode
            self.obs.watch_fleet(
                [f"actor{a}" for a in range(max(1, actor_threads))]
                + ["learner"])

        start = time.time()
        drained_n = [0]
        # episodes drain in COMPLETION order, so "max drained" could tag
        # a preemption checkpoint after an episode whose predecessors
        # never drained — the resume counter must advance only through
        # the contiguous drained prefix (the gap re-runs on resume)
        drained_set: set = set()
        prefix = [start_episode - 1]

        def on_episode(rec, ring):
            """Learner-thread drain of one actor episode: the same
            history/rewards/obs row discipline as train_parallel, in
            COMPLETION order (the episode index rides on every row and
            event, so analysis re-sorts; rewards.csv order is completion
            order — documented in README)."""
            ep = rec["episode"]
            drained_n[0] += 1
            sps = (drained_n[0] * steps_per_ep * num_replicas
                   / (time.time() - start))
            row = {"episodic_return": rec["episodic_return"],
                   "mean_succ_ratio": rec["mean_succ_ratio"],
                   "final_succ_ratio": rec["final_succ_ratio"],
                   "episode": ep, "sps": sps}
            self.history.append(row)
            self.rewards_writer.write(rec["episodic_return"])
            if self.tb:
                gs = (ep + 1) * steps_per_ep
                self.tb.add_scalar("charts/episodic_return",
                                   rec["episodic_return"], gs)
                self.tb.add_scalar("charts/SPS", sps, gs)
            if verbose:
                log.info("episode=%d actor=%d v=%d return=%.3f sps=%.1f",
                         ep, rec["actor"], rec["policy_version"],
                         rec["episodic_return"], sps)
            if curr is not None:
                curr.emit_weights(hub, ep)
            if self.obs:
                extra = {"replicas": num_replicas,
                         "actor": rec["actor"],
                         "policy_version": rec["policy_version"]}
                if mix_plan is None and factory is None:
                    extra = self._topology_extra(
                        ep, rec["episodic_return"], extra=extra)
                self.obs.episode_dispatched(ep)
                self.obs.episode_end(
                    episode=ep,
                    global_step=(ep + 1) * steps_per_ep - 1,
                    metrics={k: v for k, v in row.items()
                             if k not in ("episode", "sps")},
                    sps=sps, phases=timer.summary(),
                    replay_bytes=buffer_nbytes(ring), extra=extra)
            if hub is not None:
                # global ring fill (one [B]-vector sync per drained
                # episode — the satellite gauge that stays correct when
                # the ring lives sharded)
                hub.gauge("replay_fill_frac", buffer_fill_frac(ring))
                # this host's addressable share of the (possibly
                # dp-sharded) ring — metadata only, no sync; equals the
                # global gauge on a single host and the true per-host
                # HBM spend on a pod
                hub.gauge("replay_local_bytes",
                          buffer_nbytes(ring, local=True))
            drained_set.add(ep)
            while prefix[0] + 1 in drained_set:
                prefix[0] += 1
                drained_set.discard(prefix[0])
            self._last_drained = prefix[0]

        def on_burst(n, st, metrics):
            if curr is None:
                return
            sig = (metrics or {}).get("learn_signal") \
                if isinstance(metrics, dict) else None
            if sig is not None:
                # one [K]-vector sync per burst (K = family count):
                # the curriculum steers from LIVE burst TD here because
                # async bursts are not tied to any episode's drain
                curr.fold_td(np.asarray(sig["td_abs_sum"]),
                             np.asarray(sig["td_count"]))

        def checkpoint_fn(st, ring, n_drained):
            # same finite-verified host-layout save as train_parallel —
            # run_async's rollback guard (chaos runs) already keeps the
            # state verified, but this host scan is the last line for
            # guard-off runs; under a plan the state gathers through the
            # plan's fns so the checkpoint layout stays
            # mesh-shape-agnostic (elastic resume).  The episode tag is
            # the CONTIGUOUS drained prefix (on_episode above), so a
            # resume never skips an undrained episode.
            h_st = plan.gather_state(st) if plan is not None else st
            if self._finite_host(h_st):
                ckpt_manager.save(h_st, jax.device_get(ring),
                                  episode=self._last_drained + 1)
            else:
                self._recover(
                    self._last_drained, site="learner_state",
                    action="detected", fault="non_finite_state",
                    detail="async path has no rollback guard — "
                           "checkpoint skipped so the last-good pointer "
                           "keeps the previous verified state")

        cfg = AsyncConfig(actor_threads=actor_threads,
                          publish_bursts=publish_bursts,
                          max_staleness=max_staleness,
                          learn_ratio=learn_ratio, throttle_s=throttle_s)
        try:
            res = run_async(
                pddpg, scenario_fn, state, buffers, episodes,
                steps_per_ep, chunk, self.seed, cfg,
                publisher=publisher, hub=hub, timer=timer,
                on_episode=on_episode, on_burst=on_burst,
                should_stop=(
                    (lambda: preempt.triggered) if preempt is not None
                    else None),
                start_episode=start_episode,
                checkpoint_every=(ckpt_interval if ckpt_manager
                                  is not None else 0),
                checkpoint_fn=(checkpoint_fn if ckpt_manager is not None
                               else None),
                fault_plan=self.fault_plan,
                # the guard is chaos-scoped: a fault-free --async run
                # stays byte-identical to the guard-free stack (no
                # per-block finite dispatch, no snapshots);
                # --no-rollback still disables it under a plan
                rollback=(self.rollback and self.fault_plan is not None),
                on_recovery=self._recover,
                retry_policy=self.retry_policy)
        finally:
            if self.obs:
                # drop the per-thread watches BEFORE pausing: a paused
                # watchdog keeps its registry, and the next (sync) loop
                # must not inherit actor heartbeats nobody beats anymore
                self.obs.unwatch_fleet()
                self.obs.pause_watchdog()
        if preempt is not None and preempt.triggered:
            self.preempted = True
            self._recover(
                self._last_drained + 1, site="run",
                action="preempt_snapshot", fault=preempt.signame,
                detail="async run drained and stopped; the caller "
                       "checkpoints the drained state")
            if self.obs:
                # SIGTERM post-mortem (the PR 5 recovery path): the same
                # black-box dump a wedged fleet gets, tagged with the
                # signal — best effort, a failed dump must not block the
                # preemption snapshot itself
                try:
                    self.obs.write_blackbox(
                        reason=f"preempt:{preempt.signame}")
                except Exception:
                    log.warning("preempt black-box dump failed",
                                exc_info=True)
        self.completed_episodes = self._last_drained + 1
        self.async_info = res.info
        if self.fault_plan is not None:
            self.fault_plan.warn_unfired(hub)
        if hub is not None:
            hub.event("async_train", **res.info)
        # phases-only merge (primary=None): the async ledger splits the
        # wall per entry (actor_dispatch / learn_dispatch / replay_ingest)
        # and no single fused program owns a "dispatch" phase to attribute
        self._note_cost_timings(timer, None)
        self.rewards_writer.close()
        if self.tb:
            self.tb.close()
        return res.state, res.buffers

    def evaluate(self, state: DDPGState, episodes: int = 1,
                 test_mode: bool = True, telemetry: bool = False,
                 write_schedule: bool = False,
                 telemetry_flush_every: int = 1) -> Dict[str, float]:
        """Greedy rollout on the inference network (inference.py:17-40
        semantics: actor only, no noise, no learning).  With ``telemetry``
        the reference's test-mode CSV suite is written to
        <result_dir>/test (writer.py:16-110 schema);
        ``telemetry_flush_every`` batches the suite's per-interval file
        flushes for long sweeps (default 1 = reference behavior)."""
        writer = None
        if telemetry and self.result_dir:
            from ..utils.telemetry import TestModeWriter
            writer = TestModeWriter(
                os.path.join(self.result_dir, "test"),
                write_schedule=write_schedule,
                sf_names=self.env.service.sf_names,
                sfc_names=self.env.service.sfc_names,
                flush_every=telemetry_flush_every)
        totals = []
        succ = []
        # compile/warmup vs steady-state split: everything up to the first
        # completed control step of the first episode (env.reset + actor
        # trace + the first blocking env.step) is compile+warmup wall — on
        # a cold process it dominates the total, and hiding it inside one
        # aggregate number makes serving-path wins unmeasurable from here
        t_eval0 = time.time()
        warmup_s = None
        for ep in range(episodes):
            t_ep = time.time()
            topo, traffic = self.driver.episode(ep, test_mode)
            rng = jax.random.PRNGKey(self.seed + 10_000 + ep)
            env_state, obs = self.env.reset(rng, topo, traffic)
            ep_reward = 0.0
            infos = None
            for _ in range(self.agent_cfg.episode_steps):
                t0 = time.time()
                # the shared greedy policy fn (also the serving stack's AOT
                # target) — eager here, so the op sequence is unchanged
                action = self.ddpg.greedy_action(state.actor_params, obs)
                # algorithm runtime per control step (the adapter's
                # measurement between calls, siminterface/simulator.py:161-167);
                # block so async dispatch doesn't hide the compute time
                jax.block_until_ready(action)
                runtime = time.time() - t0
                env_state, obs, reward, done, infos = self.env.step(
                    env_state, topo, traffic, action)
                ep_reward += float(np.asarray(reward))
                if warmup_s is None:   # first step drained: compiles done
                    warmup_s = time.time() - t_eval0
                if writer:
                    # the schedule/placement the env actually applied,
                    # surfaced by env.step (no recomputation)
                    sched = infos["schedule"]
                    placement = infos["placement"]
                    t_steps = traffic.ingress_active.shape[0]
                    idx = min(int(env_state.sim.run_idx) - 1, t_steps - 1)
                    flat = (np.asarray(obs).tolist()
                            if not self.agent_cfg.graph_mode else
                            np.asarray(obs.nodes).T.reshape(-1).tolist())
                    writer.write_step(
                        episode=ep, time=float(env_state.sim.t),
                        metrics=env_state.sim.metrics, placement=placement,
                        node_cap=traffic.node_cap[max(idx, 0)],
                        schedule=sched, runtime=runtime, rl_state=flat,
                        truncated_arrivals=int(np.asarray(
                            env_state.sim.truncated_arrivals)))
            totals.append(ep_reward)
            succ.append(float(np.asarray(infos["succ_ratio"])))
            if self.obs:
                # greedy test rollouts stream through the same hub — a
                # long eval sweep is visible (and device memory sampled)
                # just like training episodes
                self.obs.eval_episode(ep, ep_reward, succ[-1],
                                      time.time() - t_ep)
        if writer:
            writer.close()
        total_s = time.time() - t_eval0
        warmup = warmup_s if warmup_s is not None else total_s
        return {"mean_return": float(np.mean(totals)),
                "final_succ_ratio": float(np.mean(succ)),
                # the split `cli infer` reports: first-step wall (compile +
                # warmup) vs everything after it — steady_s/total steps is
                # the per-request latency a serving deployment would see
                "compile_warmup_s": round(warmup, 3),
                "steady_s": round(total_s - warmup, 3),
                "total_s": round(total_s, 3)}
