"""Learning agents (reference: src/rlsp/agents/)."""
from .buffer import ReplayBuffer, buffer_add, buffer_init, buffer_sample
from .ddpg import DDPG, DDPGState
from .trainer import Trainer

__all__ = ["ReplayBuffer", "buffer_add", "buffer_init", "buffer_sample",
           "DDPG", "DDPGState", "Trainer"]
