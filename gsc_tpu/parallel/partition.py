"""Partition rules + shard/gather fns: pjit-sharded training state.

The scale-out story so far (``parallel/mesh.py``, ``parallel/dp.py``)
shards the *data* — env replicas, replay shards, traffic — over a 1-D
``dp`` mesh and keeps every parameter replicated.  This module adds the
other half of the Podracer/Anakin pattern (arXiv 2104.06272): a regex
rulebook over the ``/``-joined leaf paths of the DDPG param/opt pytree
(the ``match_partition_rules`` idiom, SNIPPETS.md [1]-[2]) producing a
``NamedSharding`` tree over a 2-D ``dp x mp`` mesh, plus per-leaf shard
and gather functions (SNIPPETS.md [3]) so any host-resident pytree can be
placed onto — or pulled off — the mesh without retracing the train step.

Two axes, two jobs:

- the REPLICA axis of every data pytree is sharded over BOTH mesh axes,
  ``P(("dp", "mp"))`` — so however the device grid is carved (``8x1``,
  ``4x2``, ``2x4``), the per-device data layout is identical (one layout
  per device COUNT, not per carving).  Every float contraction that
  touches the batch therefore keeps the same partial-sum structure across
  carvings, which is what makes the final learner state BIT-IDENTICAL
  across mesh shapes — the same invariance the multi-process dryrun
  proves for process carvings;
- parameter leaves matched by a sharding rule split their OUTPUT-feature
  (last) dimension over ``mp`` only.  An output dim is never a
  contraction dim, so each output element is still computed on exactly
  one device with the unchanged op sequence: sharded params are bit-exact
  against replicated params by construction, and against each other
  across carvings.

``REPLICATED_RULES`` (everything ``P()``) is the default rulebook — with
it the plan is a pure no-op fallback reproducing today's data-parallel
stack bit-for-bit.  Scalars and single-element leaves are never
partitioned regardless of rules, and a rule whose sharded dimension does
not divide the mesh axis is clamped back to replication (logged), so one
rulebook ports across mesh shapes and model widths unchanged.
"""
from __future__ import annotations

import logging
import re
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..meshspec import (PARTITION_RULEBOOKS, parse_mesh_shape,
                        validate_partition_rules)
from .mesh import force_virtual_cpu

log = logging.getLogger("gsc_tpu.parallel.partition")

#: axis names of the 2-D training mesh: ``dp`` carves replicas across
#: hosts/chips, ``mp`` additionally splits wide parameter matrices.
TRAIN_AXES = ("dp", "mp")

#: the no-op rulebook: every leaf replicated — byte-for-byte the
#: pre-partition data-parallel stack (params live on every device).
REPLICATED_RULES: Tuple[Tuple[str, P], ...] = ((r".*", P()),)


def sharded_rules(mp_axis: str = "mp") -> Tuple[Tuple[str, P], ...]:
    """The DDPG rulebook: wide output-feature matrices split over ``mp``.

    Matches the actor/critic MLP ``kernel`` leaves and the GATv2
    projections ``w_l``/``w_r`` — in the online params, the Polyak
    targets AND both Adam moment trees (the optimizer state mirrors the
    param paths, so one ``kernel$`` rule shards all of them alike; a
    moment sharded differently from its param would force a reshard
    every update).  Only the LAST (output-feature) dimension is sharded:
    it is never contracted over, so the op-by-op float sequence — and
    therefore the training math — is unchanged (see module docstring).
    Attention vectors (``att``: output dim 1), biases, scalars, PRNG
    keys and step counts fall through to replication.
    """
    return (
        (r"(kernel|w_l|w_r)$", P(None, mp_axis)),
        (r".*", P()),
    )


def tp_rules(mp_axis: str = "mp") -> Tuple[Tuple[str, P], ...]:
    """The TRUE tensor-parallel rulebook: contraction dims split over
    ``mp``, partial products psum-accumulated by GSPMD.

    Where :func:`sharded_rules` only ever splits output-feature dims
    (keeping the float sequence — and therefore bit-equality — intact),
    this book spends the precision contract for genuinely parallel
    compute, Megatron-style within each block:

    - first projections (``Dense_0`` kernels, GATv2 ``w_l``/``w_r``) are
      COLUMN-parallel: the hidden/feature OUTPUT dim splits over ``mp``,
      so each device computes its slice of the hidden activation;
    - deeper MLP kernels (``Dense_1``..) are ROW-parallel: the hidden
      CONTRACTION dim splits over ``mp`` — each device dots its
      activation slice against its weight rows and GSPMD psums the
      partial products (one all-reduce per column/row pair, not one per
      layer);
    - ``Dense_0`` biases follow their sharded pre-activation.

    The psum reduces shards in a carving-dependent order, so a ``tp``
    run drifts ~1e-7 per mp size against the replicated program per
    gradient step — the documented floor.  Acceptance is BANDED, not
    bit-exact: learning curves and bench rows must land inside
    ``tools/bench_diff.py``'s tolerance envelope vs a replicated control
    (ROADMAP item 2's trade).  Polyak targets and both Adam moments
    share the param paths, so one rule shards all of them alike —
    moments never reshard per update.  Attention vectors (``att``:
    contraction over the sharded feature dim — GSPMD psums the logit),
    remaining biases, scalars and PRNG keys fall through to
    replication."""
    return (
        (r"Dense_0/kernel$", P(None, mp_axis)),
        (r"Dense_0/bias$", P(mp_axis)),
        (r"Dense_\d+/kernel$", P(mp_axis, None)),
        (r"(w_l|w_r)$", P(None, mp_axis)),
        (r".*", P()),
    )


#: rulebook-name -> builder for the named books every surface accepts
#: (the vocabulary itself lives jax-free in ``gsc_tpu.meshspec``)
NAMED_RULEBOOKS = {
    "replicated": lambda: REPLICATED_RULES,
    "sharded": sharded_rules,
    "tp": tp_rules,
}
assert tuple(NAMED_RULEBOOKS) == PARTITION_RULEBOOKS


# ------------------------------------------------------------- mesh shapes
# the "DPxMP" grammar lives jax-free in gsc_tpu.meshspec (bench.py's
# orchestrator shares it without importing jax); parse_mesh_shape is
# imported above and re-exported so every historic import site keeps
# working.


def make_train_mesh(dp: int, mp: int = 1,
                    axes: Tuple[str, str] = TRAIN_AXES) -> Mesh:
    """2-D ``(dp, mp)`` mesh over the first ``dp*mp`` devices.

    Like :func:`..mesh.make_mesh`, falls back to a virtual CPU platform
    when fewer devices exist (the dry-run/CI path) — production entry
    points that must never silently leave the accelerator check device
    counts BEFORE calling (bench.py does)."""
    n = dp * mp
    devs = jax.devices()
    if len(devs) < n:
        force_virtual_cpu(n)
        devs = jax.devices()
    grid = np.asarray(devs[:n]).reshape(dp, mp)
    return Mesh(grid, axes)


# ----------------------------------------------------------- rule matching
def leaf_path_names(tree) -> List[str]:
    """``/``-joined path name per leaf, in ``tree_leaves`` order.

    ``actor_opt[0].mu['params']['MLP_0']['Dense_0']['kernel']`` becomes
    ``actor_opt/0/mu/params/MLP_0/Dense_0/kernel`` — the namespace the
    rule regexes match against."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)

    def name(entry) -> str:
        for attr in ("name", "key", "idx"):
            if hasattr(entry, attr):
                return str(getattr(entry, attr))
        return str(entry)

    return ["/".join(name(k) for k in path) for path, _ in flat]


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree) -> Any:
    """Pytree of ``PartitionSpec`` per leaf: first rule whose regex
    ``re.search``-matches the leaf's ``/``-joined path wins.

    Scalars and single-element leaves are never partitioned (``P()``)
    regardless of rules — splitting a step counter or a PRNG key buys
    nothing and breaks dtype-agnostic resume.  A leaf no rule matches is
    an error: end every rulebook with ``(".*", P())`` to make
    replication the explicit default rather than a silent one."""
    names = leaf_path_names(tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)

    def spec_for(name: str, leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        # trace-time shape arithmetic, never a traced value
        if len(shape) == 0 or int(np.prod(shape)) == 1:  # gsc-lint: disable=R1
            return P()
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        raise ValueError(f"no partition rule matched leaf {name!r} — "
                         "append a ('.*', P()) default rule")

    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(n, l) for n, l in zip(names, leaves)])


def clamp_specs_to_mesh(specs, tree, mesh: Mesh) -> Tuple[Any, int]:
    """Downgrade any spec whose sharded dimension the mesh cannot split
    evenly (or that out-ranks its leaf) to ``P()``.

    Returns ``(clamped_specs, n_clamped)``.  This is what makes ONE
    rulebook portable across mesh shapes: ``(kernel, P(None, 'mp'))``
    shards a 256-wide layer on ``mp=4`` and quietly replicates a 22-wide
    GNN projection the same mesh cannot divide — the elastic-resume path
    leans on exactly this when a checkpoint reshards onto a differently
    carved mesh."""
    names = leaf_path_names(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    flat_specs, treedef = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))
    clamped = 0

    def ok(spec: P, shape: Tuple[int, ...]) -> bool:
        if len(spec) > len(shape):
            return False
        for dim, entry in zip(shape, spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            # mesh axis sizes are Python ints — trace-time constants
            size = int(np.prod([mesh.shape[a] for a in axes]))  # gsc-lint: disable=R1
            if size > 1 and dim % size != 0:
                return False
        return True

    out = []
    for name, leaf, spec in zip(names, leaves, flat_specs):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if spec != P() and not ok(spec, shape):
            log.debug("partition rule clamped to replication: %s %s on "
                      "mesh %s", name, shape, dict(mesh.shape))
            spec = P()
            clamped += 1
        out.append(spec)
    return jax.tree_util.tree_unflatten(treedef, out), clamped


def spec_summary(specs) -> Dict[str, int]:
    """``{spec-string: leaf count}`` — the compact partition-layout
    record ``run_start`` obs meta carries (counts by spec, never the
    full tree: a rung-5 state has hundreds of leaves)."""
    counts: Dict[str, int] = {}
    for spec in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)):
        key = str(spec)
        counts[key] = counts.get(key, 0) + 1
    return dict(sorted(counts.items()))


@contextmanager
def no_persistent_compile_cache(mesh: Mesh):
    """Disable the persistent XLA compilation cache while compiling (or
    re-compiling after eviction) a MULTI-DEVICE CPU program.

    Measured on this box (jax 0.4.37): deserializing a num_partitions>1
    CPU executable from the persistent cache is broken — a cache hit
    either aborts with glibc heap corruption (``free(): invalid next
    size`` / ``double free`` / SIGSEGV) or, worse, runs and silently
    computes garbage (a 2x4 carving leg returned a DIFFERENT digest on
    every cached run where every fresh compile returns the same correct
    bytes).  Fresh compiles of the same programs are correct and
    carving-invariant.  The suite's historic multi-device test programs
    never tripped this because they compile under the 1 s
    ``persistent_cache_min_compile_time_secs`` floor and are never
    written; the sharded ``chunk_step`` compiles in seconds and is.

    Merely flipping ``jax_compilation_cache_dir`` is NOT enough: the
    cache object and the per-backend "is the cache used" verdict are
    both LATCHED at first use (``compilation_cache._initialize_cache``
    / ``is_cache_used``), so a live cache keeps serving reads whatever
    the config says.  The guard therefore calls
    ``compilation_cache.reset_cache()`` with the dir unset — the next
    compile re-initializes to "disabled" — and resets again on exit so
    the restored dir re-latches lazily.  Single-device programs and
    TPU/GPU backends round-trip fine, so the guard activates ONLY for a
    >1-device CPU mesh with a cache dir configured — everything else
    keeps its cache semantics untouched."""
    try:
        active = (len(mesh.devices.flat) > 1
                  and next(iter(mesh.devices.flat)).platform == "cpu"
                  and jax.config.jax_compilation_cache_dir)
    except Exception:
        active = False
    if not active:
        yield
        return
    from jax._src import compilation_cache as _cc
    old = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
        _cc.reset_cache()


# -------------------------------------------------------- shard/gather fns
def make_shard_and_gather_fns(shardings) -> Tuple[Any, Any]:
    """Pytrees of per-leaf ``shard(x)`` / ``gather(x)`` callables from a
    pytree of ``NamedSharding`` (the SNIPPETS.md [1]-[3] idiom).

    ``shard`` places a host or differently-placed leaf onto the mesh
    (``jax.device_put`` — a layout move, never a retrace); ``gather``
    pulls a (possibly sharded) leaf back to one host ``np.ndarray`` —
    the portable layout checkpoints are written in."""
    def make_shard(s):
        return lambda x: jax.device_put(x, s)

    def make_gather(_s):
        # gather IS the device->host sync, by contract; host-side only,
        # never called from traced code
        return lambda x: np.asarray(jax.device_get(x))  # gsc-lint: disable=R1

    is_s = lambda x: isinstance(x, NamedSharding)
    shard_fns = jax.tree_util.tree_map(make_shard, shardings, is_leaf=is_s)
    gather_fns = jax.tree_util.tree_map(make_gather, shardings, is_leaf=is_s)
    return shard_fns, gather_fns


def apply_fns(fns, tree):
    """Apply a pytree of per-leaf callables to a matching pytree."""
    return jax.tree_util.tree_map(lambda f, x: f(x), fns, tree)


# ------------------------------------------------------------ the plan
class ShardingPlan:
    """One mesh + one rulebook, bound to concrete sharding trees lazily.

    The object the sharded train path threads around: ``ParallelDDPG``
    reads ``state_shardings``/``data_sharding``/``replicated`` to build
    its ``in_shardings``/``out_shardings``, the trainer/CLI use
    ``place_state``/``gather_state`` to move the learner state on and
    off the mesh (elastic resume = ``gather`` on the old mesh shape,
    ``place`` on the new one), and obs meta records ``describe()`` +
    ``summary()``.

    ``rules`` is either a rulebook (sequence of ``(regex, spec)``) or
    one of the named books ``"replicated"`` (default — the bit-identical
    no-op fallback) / ``"sharded"`` (:func:`sharded_rules`) / ``"tp"``
    (:func:`tp_rules` — true tensor-parallel compute: the learner state
    stays RESIDENT-sharded through the compiled program, accepted under
    tolerance bands instead of bit-equality)."""

    def __init__(self, mesh: Mesh, rules="replicated"):
        self.rules_name = rules if isinstance(rules, str) else "custom"
        if isinstance(rules, str):
            rules = NAMED_RULEBOOKS[validate_partition_rules(rules)]()
        self.mesh = mesh
        self.rules = tuple(rules)
        self.dp = int(mesh.shape.get("dp", 1))
        self.mp = int(mesh.shape.get("mp", 1))
        # replicas/batch sharded over the WHOLE grid: the per-device data
        # layout depends only on dp*mp, so recarving the same devices
        # never changes a float reduction (module docstring)
        self.data_sharding = NamedSharding(mesh, P(TRAIN_AXES))
        self.replicated = NamedSharding(mesh, P())
        self._state_shardings = None   # bound on first state sighting
        self._shard_fns = None
        self._gather_fns = None
        self.clamped = 0

    @classmethod
    def from_spec(cls, spec: str, rules="replicated") -> "ShardingPlan":
        dp, mp = parse_mesh_shape(spec)
        return cls(make_train_mesh(dp, mp), rules=rules)

    @property
    def n_devices(self) -> int:
        return self.dp * self.mp

    def describe(self) -> str:
        return f"{self.dp}x{self.mp}"

    # -------------------------------------------------------- state trees
    def state_shardings(self, state):
        """NamedSharding tree for the learner state; bound once (the
        state's tree structure is static for the life of a run) and
        reused by every subsequent dispatch — shard/gather moves never
        re-derive it, hence never retrace."""
        if self._state_shardings is None:
            specs = match_partition_rules(self.rules, state)
            specs, self.clamped = clamp_specs_to_mesh(specs, state,
                                                      self.mesh)
            self._state_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            self._shard_fns, self._gather_fns = make_shard_and_gather_fns(
                self._state_shardings)
        return self._state_shardings

    def place_state(self, state):
        """Shard a host-resident (or any-mesh) learner state onto THIS
        plan's mesh — the reshard-on-load half of elastic resume."""
        return apply_fns(self._ensure_fns(state)[0], state)

    def gather_state(self, state):
        """Learner state as host ``np.ndarray`` leaves — the
        mesh-shape-agnostic layout checkpoints persist."""
        return apply_fns(self._ensure_fns(state)[1], state)

    def place_data(self, tree):
        """Shard a data pytree's leading replica axis over the grid."""
        return jax.device_put(tree, self.data_sharding)

    def place_replicated(self, tree):
        return jax.device_put(tree, self.replicated)

    def _ensure_fns(self, state):
        self.state_shardings(state)
        return self._shard_fns, self._gather_fns

    def summary(self, state_or_shapes) -> Dict[str, int]:
        """Partition layout as ``{spec: leaf count}`` (obs meta).  Works
        on concrete states AND ``jax.eval_shape`` trees — the CLI
        records it before any device work runs."""
        specs = match_partition_rules(self.rules, state_or_shapes)
        specs, _ = clamp_specs_to_mesh(specs, state_or_shapes, self.mesh)
        return spec_summary(specs)

    @property
    def is_sharded(self) -> bool:
        """True iff any rule can split a leaf (mp>1 with a non-P() rule)
        — the replicated book or an mp=1 mesh is the no-op fallback."""
        return self.mp > 1 and any(spec != P() for _, spec in self.rules)

    @property
    def resident_sharded(self) -> bool:
        """True for the ``tp`` book: the learner state stays sharded
        THROUGH the compiled program (in_/out_shardings are the plan's
        partition layout, entry-allgather/exit-slice layout moves are
        deleted, psum accumulates the partial products).  The
        replicated/sharded books keep the PR 8 ZeRO-residency design —
        sharded BETWEEN dispatches, replicated inside the program — so
        their bit-equality contract is untouched."""
        return self.rules_name == "tp"

    # --------------------------------------------------- async replay ring
    @property
    def ring_sharding(self) -> NamedSharding:
        """Sharding of the device-resident ``[B, cap]`` async replay ring
        — identical to ``data_sharding`` (replica axis 0 over the whole
        grid) ON PURPOSE: the sharded rollout already emits transition
        blocks in this layout, so an ingest whose ring, block, pos and
        size all share it is a row-aligned scatter GSPMD partitions
        per-shard with ZERO collectives.  Blocks land on the learner
        mesh once, in their final shard, and never move again."""
        return self.data_sharding

    def assert_async_capable(self):
        """Refuse meshes the decoupled actor/learner cannot shard replay
        over: a tp-only grid (``dp == 1`` with more than one device) has
        no data-parallel axis to carve the ``[B, cap]`` ring along, so
        every ingest would reshard tensor-parallel state instead of
        writing its own rows.  Raises with the recarve instructions."""
        if self.dp == 1 and self.n_devices > 1:
            raise ValueError(
                f"--async composes with --mesh over the dp axis only: "
                f"mesh {self.describe()} is tensor-parallel-only (dp=1), "
                f"so the replay ring has no dp axis to shard over. "
                f"Recarve the same {self.n_devices} devices as "
                f"{self.n_devices}x1 (pure dp) or {max(2, self.dp)}x"
                f"{self.n_devices // max(2, self.dp)}, or drop --async "
                f"to run tensor-parallel synchronously.")


def ring_shard_rows(num_replicas: int,
                    n_shards: int) -> Tuple[Tuple[int, int], ...]:
    """The STATIC row->shard map of the dp-sharded replay ring: GSPMD
    carves axis 0 of a ``P(TRAIN_AXES)``-sharded ``[B, ...]`` leaf into
    contiguous row blocks, so shard ``s`` owns rows ``[s*B/n, (s+1)*B/n)``
    — returned as one ``(lo, hi)`` per shard.  This is the contract the
    per-shard ingest heartbeats, the ``replay_shard`` flight-recorder
    tags and the parity tests all read from; it never changes for the
    life of a mesh shape."""
    B, n = int(num_replicas), int(n_shards)
    if n <= 0 or B % n != 0:
        raise ValueError(
            f"num_replicas ({B}) must divide evenly over {n} ring shards")
    per = B // n
    return tuple((s * per, (s + 1) * per) for s in range(n))


def actor_shard_assignment(n_actors: int, n_shards: int) -> Tuple[int, ...]:
    """Stable actor->dp-shard assignment: actor ``a`` reports against
    shard ``a % n_shards``, forever.  Every actor's block spans all
    shards (rollout keeps the full replica batch row-aligned), so the
    assignment is an OBSERVABILITY contract, not a routing table: it
    names which shard's ingest heartbeat an actor's blocks bump and
    which ``replay_shard`` tag its flight-recorder spans carry, so a
    cold shard points at a specific wedged actor."""
    return tuple(a % max(1, int(n_shards)) for a in range(int(n_actors)))
