"""Decoupled actor/learner training — the Sebulba shape (arXiv:2104.06272).

``Trainer.train_parallel`` interleaves acting and learning on ONE dispatch
path: the learner idles while rollouts run and vice versa.  This module
splits them:

- **Actor threads** run the jitted replica rollout continuously: each
  actor owns its own env replicas, PRNG stream and a small per-dispatch
  SCRATCH ring (capacity = one chunk), and ships every finished chunk's
  transition block — device-resident ``[B, chunk, ...]`` leaves, never a
  host copy — into the replay channel.  Between rollout dispatches the
  actor adopts newly published weights through an in-process
  :class:`~gsc_tpu.serve.fleet.VersionWatcher` (same between-dispatch
  swap discipline as the serving fleet: no batch ever mixes policy
  versions, because adoption only happens at chunk boundaries in the
  actor's own thread).

- The **learner loop** (the calling thread) owns the shared ``[B, cap]``
  replay ring: it folds queued transition blocks in via one jitted
  ``replay_ingest`` call per block (a donated in-place scatter — the
  MindSpeed-RL-style device-resident replay service; transition tensors
  never round-trip through the host on the steady path), runs
  ``learn_burst``s back-to-back on the freshest buffer state whenever its
  update budget allows, and publishes actor weights every
  ``publish_bursts`` bursts through the :class:`WeightPublisher` bus.

Off-policy staleness is the risk, so it is BOUNDED and MEASURED instead
of assumed away: ``max_staleness`` caps how many produced-but-uningested
env steps the actors may run ahead (the channel blocks the producer —
backpressure — and the wait is the ``actor_idle`` phase), the
``policy_lag`` gauge records how many published versions behind each
ingested block's acting policy was, and ``replay_lag`` gauges the
outstanding-step backlog at every ingest.  ``learn_ratio`` paces the
learner's update budget against ingested env steps (1.0 = the sync
control's one burst per B*episode_steps steps, so learning curves are
compared at matched gradient-step budgets); while the budget is unspent
the bursts dispatch back-to-back, and waiting for acting to unlock the
next burst is the ``learner_idle`` phase the ASYNC bench bounds.

Donation discipline across threads: the ParallelDDPG here must be built
with ``donate=False`` — actors hand their scratch blocks to the learner
by reference, so a donating rollout would consume buffers another thread
still reads.  The ONLY donated call is ``replay_ingest`` on the shared
ring, which exactly one thread (the learner) owns and always rebinds.

Mesh composition (``--async --mesh``): when the ParallelDDPG carries a
:class:`~gsc_tpu.parallel.partition.ShardingPlan`, the replay ring lives
dp-SHARDED on the learner mesh (``plan.ring_sharding`` — the same row
layout the sharded rollout already emits blocks in), and ``run_async``
kills the lazy-build race the old refusal guarded by pre-building
EVERYTHING before the first actor thread exists: the plan-bound dispatch
jits, then the sharded donated ingest — AOT-lowered so its partitioned
HLO can be mined and asserted collective-free (row-aligned ring/block/
cursor shardings make the scatter one independent per-shard donated
write; a block lands on the mesh once, in its final shard, and never
moves again).  The whole run executes under ONE
``no_persistent_compile_cache`` guard (the multi-device-CPU cache wart,
see partition.py), which also makes the per-dispatch inner guards
inert — no actor thread ever toggles global jax config.  Learn-bursts
dispatch through the same plan-bound binding the sync path uses (tp
rulebooks compose unchanged), and publishes gather params to host ONCE
so the actor watchers and the serving fleet's hot-swap read the same
weight bytes.

Self-healing (``fault_plan`` / ``rollback``): production fleets assume
workers die and restart routinely (Podracer, MindSpeed RL), so the loop
is SUPERVISED rather than fail-fast.  An :class:`ActorSupervisor` tracks
each actor's uncompleted episodes; a dead actor thread (exception or
injected ``actor_die``) is restarted from its episode counter within a
bounded per-actor restart budget, past which the fleet DEGRADES — the
dead actor's episodes are reassigned to survivors and the default
staleness cap is re-derived for the smaller fleet (never a hang: with
zero survivors and episodes unrun, the run raises the last actor error).
With ``rollback`` on, the learner finite-checks every popped block at
its drain boundary and QUARANTINES poisoned blocks (an evidence event
instead of an ingest — the ring never holds a NaN), and folds the
per-burst ``state_finite`` flag into a :class:`RollbackGuard`-backed
last-verified snapshot with one-burst-deferred verification, restoring
(state, ring) and continuing when a burst lands non-finite.  All of it
costs NOTHING when off: ``rollback=False`` + ``fault_plan=None`` (the
default for direct callers) adds no device dispatch, no sync and no
extra event to the fault-free path.  Every recovery flows through the
caller's ``on_recovery`` (the Trainer routes it to
``RunObserver.recovery``, same as the serial resilience ladder).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import lru_cache, partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..agents.buffer import ReplayBuffer, buffer_nbytes
from ..resilience.faults import FaultInjected
from ..resilience.guard import RollbackGuard, all_finite, poison_tree
from ..resilience.retry import (RetryPolicy, TransientDispatchError,
                                call_with_retry)
from .partition import (actor_shard_assignment, no_persistent_compile_cache,
                        ring_shard_rows)

log = logging.getLogger("gsc_tpu.parallel.async_rl")


@lru_cache(maxsize=None)
def make_replay_ingest(num_replicas: int, capacity: int, sharding=None):
    """The jitted replay service insert: fold one ``[B, T, ...]``
    transition block (an actor's scratch ring in insertion order) into
    the shared ``[B, cap, ...]`` ring at each replica's write cursor.

    The ring is DONATED — XLA scatters the block into the multi-MB replay
    in place instead of copying it per ingest — so the caller must own
    the ring exclusively and always rebind from the return (the learner
    loop does).  ``T`` is static (the actors' chunk size), so the whole
    async interleaving runs through ONE trace of this function.
    Memoized by ``(B, cap, sharding)``: a warmup ``run_async`` followed
    by a measured one (the bench split) reuses the SAME jit — the steady
    window stays zero-retrace across calls.

    With ``sharding`` (a plan's ``ring_sharding``): ring, block AND the
    per-replica cursors all carry the same row layout, and the fold runs
    under ``shard_map`` — each device scatters its OWN contiguous row
    block with LOCAL indices.  (Plain GSPMD cannot row-partition this
    scatter: the global ``[B, T]`` index arrays make it all-gather the
    ring — measured 28 all-gathers at 4 shards — while the shard_map
    body is collective-free by construction.)  The caller (``run_async``
    prewarm) AOT-lowers this jit and asserts the partitioned program
    contains ZERO collective ops."""
    B = int(num_replicas)

    def _fold(buffers: ReplayBuffer, block: Any, rows) -> ReplayBuffer:
        T = jax.tree_util.tree_leaves(block)[0].shape[1]
        # per-replica wrapped slot indices [rows, T] from the write cursor
        idx = (buffers.pos[:, None] + jnp.arange(T)[None, :]) % capacity
        data = jax.tree_util.tree_map(
            lambda d, s: d.at[rows, idx].set(s.astype(d.dtype)),
            buffers.data, block)
        return buffers.replace(
            data=data, pos=(buffers.pos + T) % capacity,
            size=jnp.minimum(buffers.size + T, capacity))

    if sharding is None:
        @partial(jax.jit, donate_argnums=(0,))
        def replay_ingest(buffers: ReplayBuffer,
                          block: Any) -> ReplayBuffer:
            return _fold(buffers, block, jnp.arange(B)[:, None])

        return replay_ingest

    from jax.experimental.shard_map import shard_map
    mesh, spec = sharding.mesh, sharding.spec

    def _local_fold(buffers: ReplayBuffer, block: Any) -> ReplayBuffer:
        # runs per-device on the shard's own rows: cursors/ring/block all
        # arrive pre-sliced, so the row indices are a local iota
        return _fold(buffers, block,
                     jnp.arange(buffers.pos.shape[0])[:, None])

    # check_rep off: every output is fully row-partitioned (nothing
    # replicated to validate) and this jax version's replication checker
    # rejects benign .at[].set patterns
    sharded_fold = shard_map(_local_fold, mesh=mesh,
                             in_specs=(spec, spec), out_specs=spec,
                             check_rep=False)

    @partial(jax.jit, donate_argnums=(0,),
             in_shardings=(sharding, sharding), out_shardings=sharding)
    def replay_ingest(buffers: ReplayBuffer, block: Any) -> ReplayBuffer:
        return sharded_fold(buffers, block)

    return replay_ingest


def _finite_host(tree) -> bool:
    """Host-side all-finite verdict (syncs the tree — publish cadence
    only, same discipline as train_parallel's pre-publish gate)."""
    return all(bool(np.isfinite(np.asarray(l)).all())
               for l in jax.tree_util.tree_leaves(tree))


# the quarantine probe: ONE device-side reduction per popped block, read
# as a single host scalar — the verdict lands host-side (the drain
# boundary's `_finite_host` discipline) without transferring the block.
# Module-level jit so a warmup/measured run pair shares the trace.
_block_finite = jax.jit(all_finite)


@dataclass
class AsyncConfig:
    """Knobs for the decoupled actor/learner loop."""

    actor_threads: int = 2
    # learner->actor weight publish cadence, in learn bursts
    publish_bursts: int = 1
    # max produced-but-uningested env steps the actors may run ahead of
    # the learner (the off-policy staleness bound; the channel BLOCKS the
    # producer past it).  0 = two full episodes per actor, the default
    # that keeps a slow learner from unbounded off-policy drift without
    # throttling a healthy fleet (one episode being acted plus one queued
    # behind the learner's ingest dispatch).
    max_staleness: int = 0
    # learner update budget per ingested env step, relative to the sync
    # control (1.0 = one burst per B*episode_steps ingested steps — the
    # matched-gradient-budget setting the curve-equivalence bands assume)
    learn_ratio: float = 1.0
    # test hook: artificial per-burst learner delay (the staleness-bound
    # tests slow the learner down to force backpressure); 0 in production
    throttle_s: float = 0.0
    # seconds the learner waits per idle poll (granularity of the
    # learner_idle phase, not a rate limit)
    idle_wait_s: float = 0.002
    # supervised restarts per ACTOR before the fleet degrades to fewer
    # actors (the dead actor's episodes are reassigned to survivors)
    restart_budget: int = 2


class _Channel:
    """Bounded actor->learner conduit of device-resident transition
    blocks.  ``put`` blocks while the outstanding (produced - ingested)
    step backlog would exceed ``max_outstanding`` — that wait IS the
    staleness backpressure.  Every block carries a global FIFO ``seq``
    (the flight recorder's put->pop flow-arrow key) plus its enqueue
    wall time and the backpressure wait it paid."""

    def __init__(self, max_outstanding: int):
        self.max_outstanding = int(max_outstanding)
        self._cond = threading.Condition()
        self._blocks: deque = deque()   # guarded-by: self._cond
        self.produced_steps = 0         # guarded-by: self._cond
        self.ingested_steps = 0         # guarded-by: self._cond
        self.max_observed_lag = 0       # guarded-by: self._cond
        self._seq = 0                   # guarded-by: self._cond
        self._stop = False              # guarded-by: self._cond

    def outstanding(self) -> int:
        # writers call this under the cond; the learner/drain monitoring
        # reads tolerate one-block staleness (ints, GIL-atomic)
        return self.produced_steps - self.ingested_steps  # gsc-lint: disable=R7 -- put() holds the cond; monitor reads tolerate staleness

    def put(self, block, steps: int, version: int, shard: int = 0,
            timer=None,
            on_wait: Optional[Callable[[float], None]] = None) -> int:
        """Enqueue one block; returns its seq (>=1, truthy), or 0 when
        the run is stopping.  ``shard`` is the producing actor's stable
        dp-shard assignment (0 on an unsharded ring) — it rides the
        queue so the learner's per-shard ingest heartbeats and the
        flight recorder's ``replay_shard`` tags attribute each block
        without a host sync.  ``on_wait(seconds)`` receives each
        backpressure slice (the per-actor idle the flight recorder
        attributes)."""
        with self._cond:
            while (not self._stop and self._blocks
                   and self.outstanding() + steps > self.max_outstanding):
                t0 = time.perf_counter()
                self._cond.wait(0.05)
                waited = time.perf_counter() - t0
                if timer is not None:
                    timer.add("actor_idle", waited)
                if on_wait is not None:
                    on_wait(waited)
            if self._stop:
                return 0
            self._seq += 1
            self._blocks.append((block, int(steps), int(version),
                                 self._seq, int(shard)))
            self.produced_steps += int(steps)
            self.max_observed_lag = max(self.max_observed_lag,
                                        self.outstanding())
            self._cond.notify_all()
            return self._seq

    def get_nowait(self):
        with self._cond:
            if not self._blocks:
                return None
            item = self._blocks.popleft()
            self.ingested_steps += item[1]
            self._cond.notify_all()
            return item

    def wait_for_data(self, timeout: float):
        with self._cond:
            if not self._blocks:
                self._cond.wait(timeout)

    def set_max_outstanding(self, n: int):
        """Re-derive the backpressure cap (fleet degrade path): blocked
        producers wake and re-check against the new bound, so shrinking
        the cap can never wedge a putter mid-wait."""
        with self._cond:
            self.max_outstanding = int(n)
            self._cond.notify_all()

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()


class _ActorPolicy:
    """In-process 'server' end of the VersionWatcher protocol for one
    actor: ``apply_weights`` runs IN the actor's own thread (poll_once is
    called between rollout dispatches), so the adopted params can never
    reach a batch mid-flight — the actor-side analogue of the fleet's
    flush-lock discipline."""

    def __init__(self, treedef):
        self.treedef = treedef
        self.policy_version = 0
        self.params = None

    def apply_weights(self, leaves, version: int, fingerprint,
                      meta: Optional[Dict] = None):
        self.params = jax.tree_util.tree_unflatten(self.treedef,
                                                   list(leaves))
        self.policy_version = int(version)


class ActorSupervisor:
    """Per-actor episode bookkeeping + the restart/degrade policy.

    Each actor owns an ordered queue of its UNCOMPLETED episodes (seeded
    with its strided assignment).  ``claim`` returns the head WITHOUT
    popping — an actor that dies mid-episode re-runs that episode from
    its start on restart (``complete`` pops only after the episode's
    stats are staged, so a finished episode is never re-run; chunks a
    dying actor already shipped are ingested twice on the re-run —
    benign replay duplicates, never corruption, and drained records
    never duplicate because stats only append at completion).

    Failures queue here and the LEARNER loop supervises: within the
    per-actor ``restart_budget`` it spawns a fresh thread resuming from
    the dead actor's episode counter; past it the actor is degraded out
    — its remaining episodes move to the orphan queue that surviving
    actors drain after their own assignments (episode data is
    scenario/seed-keyed by GLOBAL index, so WHO runs an episode never
    changes WHAT it trains on).  With zero survivors and episodes still
    unrun the learner raises the last actor error — the fleet never
    hangs and never silently under-runs."""

    def __init__(self, assignments: Dict[int, List[int]],
                 restart_budget: int):
        self._lock = threading.Lock()
        # aid -> uncompleted episodes in run order (head = next to
        # (re)run); guarded-by: self._lock
        self._remaining = {aid: deque(eps)
                           for aid, eps in assignments.items()}
        self._orphans: deque = deque()     # guarded-by: self._lock
        self._failures: deque = deque()    # guarded-by: self._lock
        self.restart_budget = int(restart_budget)
        # restarts/dead/errors: mutated by the learner thread only (the
        # single supervisor), read post-join — no extra locking needed
        self.restarts = {aid: 0 for aid in assignments}
        self.dead: set = set()
        self.errors: List[BaseException] = []

    def claim(self, aid: int) -> Optional[int]:
        """The actor's next episode (head, not popped), refilled from a
        degraded actor's orphans once its own queue drains; None when
        there is nothing left to run."""
        with self._lock:
            q = self._remaining[aid]
            if not q and self._orphans:
                q.append(self._orphans.popleft())
            return q[0] if q else None

    def complete(self, aid: int, episode: int):
        with self._lock:
            q = self._remaining[aid]
            if q and q[0] == episode:
                q.popleft()

    def report_failure(self, aid: int, episode: int, exc: BaseException):
        """Called from the dying actor thread; the learner's supervise
        pass decides restart vs degrade."""
        with self._lock:
            self._failures.append((aid, episode, exc))

    def pop_failure(self):
        with self._lock:
            return self._failures.popleft() if self._failures else None

    def note_restart(self, aid: int) -> int:
        self.restarts[aid] += 1
        return self.restarts[aid]

    def degrade(self, aid: int, exc: BaseException) -> int:
        """Move the dead actor's episodes to the orphan queue; returns
        the number of actors still alive."""
        with self._lock:
            self.dead.add(aid)
            self._orphans.extend(self._remaining[aid])
            self._remaining[aid].clear()
            self.errors.append(exc)
            return len(self._remaining) - len(self.dead)

    def unrun(self) -> int:
        with self._lock:
            return (sum(len(q) for q in self._remaining.values())
                    + len(self._orphans))

    def total_restarts(self) -> int:
        return sum(self.restarts.values())


class _FlightLedger:
    """Host-side flight recorder for one ``run_async``: actor threads and
    the learner append plain tuples (one lock, one list append — no
    device syncs, no event emission on the dispatch path); the run end
    flushes everything as compact deferred events (``async_actor_ep``,
    ``async_learner_spans``) that :func:`gsc_tpu.obs.trace.build_trace`
    reconstructs per-actor / channel / learner tracks plus put->pop and
    publish->adopt flow arrows from.  Timestamps are ``time.time()``
    (the event stream's wall base, so the reconstructed spans land on
    the same axis as every other track).

    Row shapes (positional, kept terse because they land in JSONL):

    - actor episode: ``{ep, actor, shard, chunks: [[t0, t1, ver], ...],
      puts: [[t_enq, wait_s, steps, ver, seq], ...],
      adopts: [[ts, ver], ...]}``
    - ingest: ``[t0, t1, steps, ver, lag, seq, shard]`` (``shard`` is
      the producing actor's dp-shard assignment — the ``replay_shard``
      tag on the reconstructed learner spans; 0 on an unsharded ring)
    - burst: ``[t0, t1, n]`` / publish: ``[ts, ver]``
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.actor_eps: List[Dict] = []   # guarded-by: self._lock
        self.ingests: List[List] = []     # guarded-by: self._lock
        self.bursts: List[List] = []      # guarded-by: self._lock
        self.publishes: List[List] = []   # guarded-by: self._lock

    def note_actor_episode(self, rec: Dict):
        with self._lock:
            self.actor_eps.append(rec)

    def note_ingest(self, t0, t1, steps, version, lag, seq, shard=0):
        with self._lock:
            self.ingests.append([round(t0, 6), round(t1, 6), int(steps),
                                 int(version), int(lag), int(seq),
                                 int(shard)])

    def note_burst(self, t0, t1, n):
        with self._lock:
            self.bursts.append([round(t0, 6), round(t1, 6), int(n)])

    def note_publish(self, ts, version):
        with self._lock:
            self.publishes.append([round(ts, 6), int(version)])

    def flush_deferred(self, hub, chunk_rows: int = 256):
        """Emit the deferred event records (run end, learner thread).
        Learner spans chunk at ``chunk_rows`` rows per event so one
        record never outgrows the rotating sink's line budget."""
        with self._lock:
            actor_eps = list(self.actor_eps)
            ingests = list(self.ingests)
            bursts = list(self.bursts)
            publishes = list(self.publishes)
        for rec in actor_eps:
            hub.event("async_actor_ep", **rec)
        total = max(len(ingests), len(bursts), len(publishes))
        parts = max(1, -(-total // chunk_rows))
        for p in range(parts):
            lo, hi = p * chunk_rows, (p + 1) * chunk_rows
            hub.event("async_learner_spans", part=p, parts=parts,
                      ingests=ingests[lo:hi], bursts=bursts[lo:hi],
                      publishes=publishes[lo:hi])


@dataclass
class AsyncResult:
    """What one decoupled run produced, for the caller's bookkeeping."""

    state: Any
    buffers: Any
    episodes: List[Dict] = field(default_factory=list)   # completion order
    info: Dict = field(default_factory=dict)


def run_async(pddpg, scenario_fn: Callable, state, buffers,
              episodes: int, episode_steps: int, chunk: int, seed: int,
              cfg: AsyncConfig, publisher=None, hub=None, timer=None,
              on_episode: Optional[Callable] = None,
              on_burst: Optional[Callable] = None,
              should_stop: Optional[Callable] = None,
              start_episode: int = 0, checkpoint_every: int = 0,
              checkpoint_fn: Optional[Callable] = None,
              fault_plan=None, rollback: bool = False,
              on_recovery: Optional[Callable] = None,
              retry_policy=None) -> AsyncResult:
    """Drive ``episodes - start_episode`` episodes through
    ``cfg.actor_threads`` rollout threads feeding the learner loop (the
    calling thread).  ``scenario_fn(ep) -> (topo, traffic)`` supplies
    episode ``ep``'s scenario (called from actor threads under one shared
    lock — host scenario production stays serialized and
    episode-deterministic).  ``on_episode(record, buffers)`` fires in
    the LEARNER thread as each actor episode's stats drain (record
    carries episode / return / succ ratios / policy_version / actor;
    buffers is the live ring, for fill/bytes gauges).  ``on_burst(n,
    state, metrics)`` fires after each learn burst (metrics are live
    device values — callers must not sync them in the hot loop).
    ``should_stop()`` polled at episode boundaries (preemption).
    ``checkpoint_fn(state, buffers, episodes_drained)`` fires in the
    learner thread every ``checkpoint_every`` drained episodes — the
    only thread that owns the carries, so a save can never race a
    rebind.

    With a plan-carrying ``pddpg`` (``--async --mesh``) the whole run
    executes under ONE ``no_persistent_compile_cache`` guard and a
    prewarm builds every jit before the first actor thread starts: the
    plan-bound dispatch, then the dp-sharded donated ingest (AOT-lowered
    and asserted collective-free).  The ring is placed into
    ``plan.ring_sharding`` residency here, so callers may hand a
    single-device ring.  Tp-only meshes (no dp axis) are refused up
    front via ``plan.assert_async_capable()``.

    Self-healing: ``fault_plan`` (a
    :class:`~gsc_tpu.resilience.faults.FaultPlan`) arms the fleet's
    injection sites (``actor_die``/``ring_poison``/``watcher_stall``
    keyed by actor episode, ``nan_grads``/``learner_transient`` keyed by
    learn-burst index); ``rollback=True`` arms the drain-boundary block
    quarantine and the burst-deferred :class:`RollbackGuard` snapshot;
    ``on_recovery(episode, site=, action=, fault=, attempt=, detail=)``
    receives every recovery (the Trainer routes it to
    ``RunObserver.recovery``); ``retry_policy`` bounds the transient
    learn-burst retries.  Actor supervision (restart within
    ``cfg.restart_budget``, then degrade) is ALWAYS on — a dead actor
    only kills the run once the whole fleet is exhausted.  The module
    docstring has the full ladder; everything here is free when the
    knobs stay at their defaults.

    Returns an :class:`AsyncResult`; ``info`` carries the drain-proved
    accounting: produced == ingested steps (no transition lost), the
    learner idle fraction, burst count, publish count, the observed
    policy/replay lag extrema, the self-healing ledger
    (``actor_restarts``/``actors_degraded``/``blocks_quarantined``/
    ``rollbacks``) and — under a plan — ``ring_shards`` and the
    AOT-mined ``ingest_collectives`` (always 0, by assertion)."""
    plan = getattr(pddpg, "plan", None)
    if plan is not None:
        plan.assert_async_capable()
        # ONE guard for the whole run (prewarm compiles, actor-thread
        # dispatches, learner ingests/bursts): inside it the per-dispatch
        # guards in dp.py read an unset cache dir and become inert, so no
        # actor thread ever touches global jax config (the guard itself
        # is not thread-safe — holding it once here is what makes the
        # multi-device-CPU cache wart safe under threads)
        with no_persistent_compile_cache(plan.mesh):
            return _run_async_impl(
                pddpg, scenario_fn, state, buffers, episodes,
                episode_steps, chunk, seed, cfg, publisher=publisher,
                hub=hub, timer=timer, on_episode=on_episode,
                on_burst=on_burst, should_stop=should_stop,
                start_episode=start_episode,
                checkpoint_every=checkpoint_every,
                checkpoint_fn=checkpoint_fn, fault_plan=fault_plan,
                rollback=rollback, on_recovery=on_recovery,
                retry_policy=retry_policy)
    return _run_async_impl(
        pddpg, scenario_fn, state, buffers, episodes, episode_steps,
        chunk, seed, cfg, publisher=publisher, hub=hub, timer=timer,
        on_episode=on_episode, on_burst=on_burst, should_stop=should_stop,
        start_episode=start_episode, checkpoint_every=checkpoint_every,
        checkpoint_fn=checkpoint_fn, fault_plan=fault_plan,
        rollback=rollback, on_recovery=on_recovery,
        retry_policy=retry_policy)


def _run_async_impl(pddpg, scenario_fn: Callable, state, buffers,
                    episodes: int, episode_steps: int, chunk: int,
                    seed: int, cfg: AsyncConfig, publisher=None, hub=None,
                    timer=None, on_episode: Optional[Callable] = None,
                    on_burst: Optional[Callable] = None,
                    should_stop: Optional[Callable] = None,
                    start_episode: int = 0, checkpoint_every: int = 0,
                    checkpoint_fn: Optional[Callable] = None,
                    fault_plan=None, rollback: bool = False,
                    on_recovery: Optional[Callable] = None,
                    retry_policy=None) -> AsyncResult:
    """The loop body of :func:`run_async` (which owns the plan
    validation and the run-wide compile-cache guard)."""
    from ..serve.fleet import VersionWatcher, WeightPublisher

    B = pddpg.B
    if episode_steps % chunk != 0:
        raise ValueError(f"chunk ({chunk}) must divide episode_steps "
                         f"({episode_steps})")
    cap = int(jax.tree_util.tree_leaves(buffers.data)[0].shape[1])
    if cap < chunk:
        raise ValueError(
            f"replay capacity per replica ({cap}) must be >= chunk "
            f"({chunk}) — a single ingest would wrap past itself")
    n_actors = max(1, int(cfg.actor_threads))
    total_eps = episodes - start_episode
    if total_eps <= 0:
        return AsyncResult(state=state, buffers=buffers)
    # default backlog cap: TWO episodes' worth of steps per actor — one
    # being acted plus one queued behind the learner's ingest dispatch
    # (which can wait on the ring's in-flight burst readers); a
    # one-episode cap throttles a healthy fleet into device bubbles
    # while the policy-version lag stays burst-paced (~1-2) either way
    max_stale = (int(cfg.max_staleness) if cfg.max_staleness > 0
                 else 2 * n_actors * B * episode_steps)
    channel = _Channel(max_stale)
    results: deque = deque()
    results_lock = threading.Lock()
    stop_event = threading.Event()
    # the actors' first dispatches serialize under this lock so each
    # entry point traces exactly once (two threads racing an empty jit
    # cache would both trace — the zero-retrace contract forbids that)
    compile_lock = threading.Lock()
    scenario_lock = threading.Lock()
    # quarantine + burst-rollback machinery only exists on guarded runs:
    # the bare path (no plan, no rollback) dispatches nothing extra
    guarded = rollback or fault_plan is not None

    def recover(episode, site, action, fault=None, attempt=None,
                detail=None):
        if on_recovery is not None:
            on_recovery(episode, site=site, action=action, fault=fault,
                        attempt=attempt, detail=detail)
        else:
            log.warning("recovery: site=%s action=%s fault=%s "
                        "episode=%s %s", site, action, fault, episode,
                        detail or "")

    if publisher is None:
        # in-process channel only; the plan rides along so
        # publish_corrupt@v<N> can corrupt the zero-copy path too
        publisher = WeightPublisher(hub=hub, fault_plan=fault_plan)
    elif fault_plan is not None and getattr(publisher, "fault_plan",
                                            None) is None:
        publisher.fault_plan = fault_plan

    plan = getattr(pddpg, "plan", None)
    n_shards = plan.n_devices if plan is not None else 1
    # stable actor->dp-shard assignment (observability contract: which
    # shard's heartbeat each actor's blocks bump — see partition.py)
    shard_of = actor_shard_assignment(n_actors, n_shards)
    # the multi-device enqueue-order serializer (see
    # ParallelDDPG.dispatch_lock): rollout/learn_burst dispatches
    # already hold it inside their wrappers; the learner's ingest
    # dispatch below shares it.  Single-device runs hold nothing.
    dispatch_lock = getattr(pddpg, "dispatch_lock", None) \
        if plan is not None else None
    if dispatch_lock is None:
        dispatch_lock = _noop()
    ingest_collectives = None
    if plan is not None:
        # ---- prewarm: every jit exists BEFORE the first actor thread —
        # the lazy-build race the old --mesh refusal guarded is dead
        # code on this path.  (1) the plan-bound dispatch binding (one
        # build populates rollout/chunk/learn jits);
        pddpg.sharded_lowerable("rollout_episodes", state)
        # (2) the ring's resident layout: rows carved over the dp grid
        # exactly like the blocks the sharded rollout emits (a no-op
        # when the caller already placed it);
        buffers = jax.device_put(buffers, plan.ring_sharding)
        ring_shard_rows(B, n_shards)   # validates B % shards == 0
        # (3) the per-shard donated ingest, AOT-lowered so the
        # PARTITIONED program's HLO proves the hot path moves nothing:
        # zero gather/reshard/collective ops, just each shard's own
        # row-aligned scatter.  The compiled executable IS the dispatch
        # handle — block shapes are static, so the steady state cannot
        # retrace by construction.
        from ..analysis.hlo import collective_stats
        ingest_jit = make_replay_ingest(B, cap,
                                        sharding=plan.ring_sharding)

        def _placed_zeros(leaf_shape_fn, tree):
            return jax.tree_util.tree_map(
                lambda l: jax.device_put(
                    jnp.zeros(leaf_shape_fn(l), l.dtype),
                    plan.ring_sharding), tree)

        warm_ring = _placed_zeros(lambda l: l.shape, buffers)
        warm_block = _placed_zeros(
            lambda l: (l.shape[0], chunk) + l.shape[2:], buffers.data)
        compiled = ingest_jit.lower(warm_ring, warm_block).compile()
        stats = collective_stats(compiled.as_text())
        ingest_collectives = int(stats["count"])
        if ingest_collectives:
            raise RuntimeError(
                f"dp-sharded replay_ingest compiled with "
                f"{ingest_collectives} collective op(s) "
                f"({sorted(stats['ops'])}) — the ingest hot path must "
                f"be a pure per-shard write; the ring/block shardings "
                f"have diverged from plan.ring_sharding")
        replay_ingest = compiled
        del warm_ring, warm_block   # donation fodder, never dispatched
    else:
        replay_ingest = make_replay_ingest(B, cap)
    treedef = jax.tree_util.tree_structure(state.actor_params)
    base = jax.random.PRNGKey(seed)

    # episode ownership: actor a runs global episodes start+a, start+a+A,
    # ... — deterministic regardless of thread scheduling
    def actor_episodes(aid):
        return range(start_episode + aid, episodes, n_actors)

    supervisor = ActorSupervisor(
        {a: list(actor_episodes(a)) for a in range(n_actors)},
        restart_budget=cfg.restart_budget)
    # last successful publish (version, params): a restarted actor seeds
    # its policy from here — its fresh watcher inbox only sees FUTURE
    # publishes.  Written by the learner thread, read by (re)starting
    # actors; the tuple rebind is atomic and the params tree immutable.
    latest_pub: List = [None]

    policy_lags: List[int] = []
    # flight recorder: the ledger only exists when the hub keeps series
    # history — with it off, run_async emits not one extra event and the
    # stream stays byte-identical to the pre-recorder pipeline
    ledger = (_FlightLedger() if hub is not None
              and getattr(hub, "series_store", None) is not None else None)
    # per-actor backpressure wait accumulators (each slot written by its
    # own actor thread only) — the live actor_idle_frac probes read them
    actor_wait_s = [0.0] * n_actors
    learner_idle_acc = [0.0]

    # the actors' starting point, bound BEFORE the learner loop ever
    # rebinds `state`: a restarted actor must stage from the same
    # published-or-initial params as a first start, never from whatever
    # unpublished learner state happens to be live at restart time
    # (donate=False on this path keeps these buffers valid for the whole
    # run)
    init_state = state

    def actor_loop(aid: int):
        tname = f"actor{aid}"
        policy = _ActorPolicy(treedef)
        watcher = VersionWatcher(None, policy, hub=hub,
                                 publisher=publisher)
        # every actor starts from the published-or-initial params with
        # its OWN rng stream (identical streams would collapse the
        # exploration the replica axis exists to diversify)
        a_state = init_state.replace(
            rng=jax.random.fold_in(init_state.rng, 1000 + aid))
        pub = latest_pub[0]
        if pub is not None:
            # a RESTARTED actor re-adopts the latest published weights
            # instead of regressing to the initial params (its fresh
            # inbox only sees future publishes); on the first start
            # nothing has been published and this is a no-op
            policy.apply_weights(
                jax.tree_util.tree_leaves(pub[1]), pub[0], None)
            a_state = a_state.replace(actor_params=policy.params)
        first = True
        n_chunks = episode_steps // chunk
        ep = -1   # the episode in flight, for the failure report

        def on_wait(waited: float):
            # one slot per actor, written only by this thread
            actor_wait_s[aid] += waited
            if hub is not None:
                hub.beat(tname)   # a backpressured actor is NOT wedged

        try:
            while True:
                if stop_event.is_set():
                    return
                nxt = supervisor.claim(aid)
                if nxt is None:
                    return
                ep = nxt
                if fault_plan is not None and fault_plan.fire(
                        "actor_die", ep, actor=aid) is not None:
                    raise FaultInjected(
                        f"injected actor death: actor_die@a{aid}:{ep}")
                with scenario_lock:
                    topo, traffic = scenario_fn(ep)
                lock = compile_lock if first else None
                if lock is not None:
                    lock.acquire()
                try:
                    env_states, obs = pddpg.reset_all(
                        jax.random.fold_in(
                            jax.random.PRNGKey(seed + ep + 2), 0),
                        topo, traffic)
                    if first:
                        one_obs = jax.tree_util.tree_map(
                            lambda x: x[0], obs)
                        scratch = pddpg.init_buffers(one_obs,
                                                     capacity=chunk)
                    chunk_stats = []
                    chunks = []
                    puts = []
                    adopts = []
                    for c in range(n_chunks):
                        # between-dispatch weight adoption: poll_once
                        # runs HERE, in the actor's own thread, so a
                        # swap can never land mid-batch (the fleet's
                        # flush-lock discipline, by construction)
                        if hub is not None:
                            hub.note_thread_phase(tname, "adopt")
                        try:
                            spec = (fault_plan.fire("watcher_stall", ep,
                                                    actor=aid)
                                    if fault_plan is not None else None)
                            if spec is not None:
                                if spec.arg:
                                    time.sleep(float(spec.arg))
                                raise FaultInjected(
                                    f"injected watcher stall: "
                                    f"watcher_stall@a{aid}:{ep}")
                            swapped = watcher.poll_once()
                        except Exception as e:
                            # a stalled/failing poll must not kill the
                            # actor: skip THIS adoption, keep acting on
                            # the current weights, adopt next chunk
                            swapped = False
                            recover(ep, site="watcher",
                                    action="skip_adopt",
                                    fault=type(e).__name__,
                                    detail=f"actor {aid}: {e}")
                        if swapped:
                            a_state = a_state.replace(
                                actor_params=policy.params)
                            if ledger is not None:
                                adopts.append([round(time.time(), 6),
                                               policy.policy_version])
                        start = jnp.int32(ep * episode_steps + c * chunk)
                        if hub is not None:
                            hub.note_thread_phase(tname, "dispatch")
                        t_roll = time.time()
                        with (timer.phase("actor_dispatch") if timer
                              else _noop()):
                            # R8 disabled below: the sharded binding's
                            # wrapper takes dispatch_lock itself
                            # (dp._bind_sharded_dispatch); the single-
                            # device path has no partition rendezvous
                            # to serialize
                            (a_state, scratch, env_states, obs,
                             stats) = pddpg.rollout_episodes(  # gsc-lint: disable=R8 -- wrapper holds dispatch_lock
                                a_state, scratch, env_states, obs,
                                topo, traffic, start, chunk)
                        if ledger is not None:
                            chunks.append([round(t_roll, 6),
                                           round(time.time(), 6),
                                           policy.policy_version])
                        chunk_stats.append(stats)
                        if hub is not None:
                            hub.note_thread_phase(tname, "blocked_put")
                        out_block = scratch.data
                        if fault_plan is not None and fault_plan.fire(
                                "ring_poison", ep) is not None:
                            # poison a COPY: scratch is this actor's
                            # live carry for the next rollout dispatch
                            out_block = poison_tree(scratch.data)
                        wait0 = actor_wait_s[aid]
                        seq = channel.put(out_block, B * chunk,
                                          policy.policy_version,
                                          shard=shard_of[aid],
                                          timer=timer, on_wait=on_wait)
                        if not seq:
                            return
                        if ledger is not None:
                            puts.append([
                                round(time.time(), 6),
                                round(actor_wait_s[aid] - wait0, 6),
                                B * chunk, policy.policy_version, seq])
                        if hub is not None:
                            hub.beat(tname)   # liveness = chunk cadence
                finally:
                    if lock is not None:
                        lock.release()
                        first = False
                if ledger is not None:
                    ledger.note_actor_episode({
                        "ep": ep, "actor": aid, "shard": shard_of[aid],
                        "chunks": chunks, "puts": puts, "adopts": adopts})
                with results_lock:
                    results.append({"episode": ep, "actor": aid,
                                    "policy_version":
                                        policy.policy_version,
                                    "chunk_stats": chunk_stats})
                supervisor.complete(aid, ep)
        except BaseException as e:   # supervised by the learner loop
            supervisor.report_failure(aid, ep, e)
            log.exception("actor %d died", aid)
        finally:
            watcher.stop()   # drops the publisher subscription; an
            # externally-owned publisher must not keep dead inboxes fed

    threads = [threading.Thread(target=actor_loop, args=(a,),
                                name=f"gsc-actor-{a}", daemon=True)
               for a in range(n_actors)]
    steps_per_burst = B * episode_steps   # the sync control's cadence
    bursts = publishes = last_ckpt = 0
    blocks_quarantined = steps_quarantined = 0
    drained: List[Dict] = []
    last_metrics = None
    guard = None
    pending_verify = None   # (burst_idx, device flag) awaiting its sync
    if rollback:
        guard = RollbackGuard()
        # seed with the (trivially finite) entry state so a poisoned
        # FIRST burst still has a restore target
        guard.init(start_episode - 1, state, buffers)
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    if hub is not None:
        # live idle-fraction probes: a mid-run /metrics scrape reads the
        # CURRENT fractions, not the last event-writer sample.  Replaced
        # by plain final gauges (and dropped) at run end.
        def _idle_probe(slot, acc):
            def probe():
                wall = time.perf_counter() - t_start
                return acc[slot] / wall if wall > 0 else 0.0
            return probe
        for a in range(n_actors):
            hub.live_gauge("actor_idle_frac",
                           _idle_probe(a, actor_wait_s), actor=a)
        hub.live_gauge("learner_idle_frac",
                       _idle_probe(0, learner_idle_acc))

    def allowance() -> int:
        return int(channel.ingested_steps * cfg.learn_ratio
                   // steps_per_burst)

    def maybe_publish(force: bool = False):
        nonlocal publishes
        if not force and (cfg.publish_bursts <= 0
                          or bursts % cfg.publish_bursts != 0):
            return
        if plan is not None:
            # ONE gather per publish: pull the (possibly resident-
            # sharded) actor params to host numpy here, once.  The
            # publisher's npz flatten is then a zero-copy pass-through
            # and every in-process subscriber (actor watchers) receives
            # the same host leaves the serving fleet's hot-swap reads
            # from disk — one publisher, two consumers, one gather.
            params = jax.tree_util.tree_map(
                lambda l: np.asarray(jax.device_get(l)),
                state.actor_params)
            finite = _finite_host(params)
        else:
            params = state.actor_params
            finite = _finite_host(params)
        if finite:
            # verified=True: the gate above already proved the leaves
            # finite, so the publisher skips its own (redundant) scan
            publisher.publish(params, meta={"burst": bursts,
                                            "episodes": len(drained)},
                              verified=True)
            latest_pub[0] = (publisher.version, params)
            publishes += 1
            if ledger is not None:
                ledger.note_publish(time.time(), publisher.version)
        else:
            log.warning("non-finite actor params at burst %d — publish "
                        "skipped so a poisoned state never reaches the "
                        "actors", bursts)
            if hub is not None:
                hub.counter("async_publish_skipped_total")

    def do_rollback(episode, detail):
        nonlocal state, buffers, pending_verify
        tag, s, b = guard.restore()
        state, buffers = s, b   # fresh copies — donation-safe carries
        pending_verify = None   # descendants of the poisoned state
        recover(episode, site="learner_state", action="rollback",
                fault="non_finite_state",
                detail=f"{detail}; restored last-verified snapshot "
                       f"(tag {tag})")
        if hub is not None:
            hub.counter("async_rollbacks_total")

    def verify_pending():
        """One-burst-deferred finite verdict: the LAST burst's
        ``state_finite`` flag syncs here (a single device scalar) right
        before the next burst dispatches — the flag's compute has had a
        full loop pass to finish, so the read rarely blocks the hot
        path.  Finite promotes the live carries to the guard's
        last-verified snapshot (blocks ingested since the burst are
        quarantine-checked, so the ring is still clean); non-finite
        restores that snapshot and the run continues."""
        nonlocal pending_verify
        if guard is None or pending_verify is None:
            return
        b_idx, flag = pending_verify
        pending_verify = None
        if bool(float(flag) > 0.0):
            guard.promote(b_idx, state, buffers, pending_empty=True)
        else:
            do_rollback(len(drained),
                        f"learn-burst {b_idx} landed non-finite")

    def check_stop():
        # polled at EVERY progress point, not just the outer loop top: a
        # fast actor fleet can finish the whole run inside one inner
        # ingest/drain pass, and a stop that only lands between passes
        # would never actually stop anything
        if should_stop is not None and not stop_event.is_set() \
                and should_stop():
            stop_event.set()   # actors stop at the next boundary; the
            # learner still DRAINS everything already produced

    def drain_results():
        while True:
            check_stop()
            with results_lock:
                if not results:
                    return
                rec = results.popleft()
            stats = rec.pop("chunk_stats")
            # device scalars, synced HERE (learner thread) so actors
            # never block on a host round-trip
            rec["episodic_return"] = sum(
                float(s["episodic_return"]) for s in stats)
            rec["mean_succ_ratio"] = (sum(
                float(s["mean_succ_ratio"]) for s in stats) / len(stats))
            rec["final_succ_ratio"] = float(
                stats[-1]["final_succ_ratio"])
            flags = [float(s["state_finite"]) for s in stats
                     if "state_finite" in s]
            rec["state_finite"] = bool(min(flags) > 0) if flags else None
            if guard is not None and rec["state_finite"] is False:
                # the actor acted on a non-finite state: same restore
                # path as a poisoned burst — the per-episode flag folds
                # into the guard instead of merely riding the record
                do_rollback(rec["episode"],
                            f"episode {rec['episode']} drained with a "
                            f"non-finite state flag")
            drained.append(rec)
            if on_episode is not None:
                on_episode(rec, buffers)

    actors_alive = lambda: any(t.is_alive() for t in threads)  # noqa: E731

    def spawn_actor(aid: int, suffix: str = ""):
        t = threading.Thread(target=actor_loop, args=(aid,),
                             name=f"gsc-actor-{aid}{suffix}", daemon=True)
        threads.append(t)
        t.start()

    def supervise():
        """Drain queued actor failures (learner thread only): restart
        within the per-actor budget, else degrade the fleet — reassign
        the dead actor's episodes to survivors and re-derive the default
        staleness cap for the smaller fleet."""
        while True:
            fail = supervisor.pop_failure()
            if fail is None:
                return
            aid, at_ep, exc = fail
            if stop_event.is_set():
                # stopping anyway: record the death, respawn nothing
                supervisor.degrade(aid, exc)
                continue
            if supervisor.restarts[aid] < supervisor.restart_budget:
                n = supervisor.note_restart(aid)
                recover(at_ep, site="actor", action="restart",
                        fault=type(exc).__name__, attempt=n,
                        detail=f"actor {aid} died at episode {at_ep}; "
                               f"restarting from its episode counter "
                               f"({n}/{supervisor.restart_budget})")
                if hub is not None:
                    hub.counter("actor_restarts_total")
                spawn_actor(aid, suffix=f"-r{n}")
            else:
                alive = supervisor.degrade(aid, exc)
                detail = (f"actor {aid} exhausted its restart budget "
                          f"({supervisor.restart_budget}); fleet "
                          f"degrades to {alive} actor(s)")
                if cfg.max_staleness <= 0 and alive > 0:
                    new_cap = 2 * alive * B * episode_steps
                    channel.set_max_outstanding(new_cap)
                    detail += f"; staleness cap re-derived to {new_cap}"
                recover(at_ep, site="actor", action="degrade",
                        fault=type(exc).__name__, detail=detail)
                if hub is not None:
                    hub.counter("actor_degraded_total")

    try:
        while True:
            supervise()
            check_stop()
            progressed = False
            # pop EVERYTHING queued before dispatching a single ingest:
            # the pop is what releases the staleness backpressure, and an
            # ingest dispatch can block on the ring's pending readers
            # (donating the ring while the in-flight learn_burst still
            # samples it makes the runtime wait for the burst) — popping
            # first keeps the actors dispatching through that wait
            # instead of stalling the whole fleet one pop per blocked
            # dispatch
            items = []
            item = channel.get_nowait()
            while item is not None:
                items.append(item)
                item = channel.get_nowait()
            for block, steps, version, seq, shard in items:
                if guarded:
                    # drain-boundary quarantine: ONE device reduction +
                    # one scalar host read per popped block.  A poisoned
                    # block is DROPPED with an evidence row — the ring
                    # never holds a NaN, and the drain accounting still
                    # balances (the pop already counted the steps as
                    # ingested; the quarantined tally rides info).
                    with dispatch_lock:
                        block_ok = bool(float(_block_finite(block)) > 0.0)
                    if not block_ok:
                        blocks_quarantined += 1
                        steps_quarantined += int(steps)
                        recover(len(drained), site="replay",
                                action="quarantine",
                                fault="non_finite_block",
                                detail=f"seq={seq} shard={shard} "
                                       f"steps={steps} version={version}")
                        if hub is not None:
                            hub.counter("replay_quarantined_total")
                            hub.event("replay_quarantine", seq=int(seq),
                                      shard=int(shard), steps=int(steps),
                                      policy_version=int(version))
                        progressed = True
                        check_stop()
                        continue
                if hub is not None:
                    hub.note_thread_phase("learner", "ingest")
                t_ing = time.time()
                with (timer.phase("replay_ingest") if timer
                      else _noop()):
                    # a multi-device ingest dispatch must not interleave
                    # its per-device enqueues with an actor's rollout
                    # dispatch (the XLA:CPU rendezvous deadlock — see
                    # ParallelDDPG.dispatch_lock); single-device runs
                    # hold no lock
                    with dispatch_lock:
                        buffers = replay_ingest(buffers, block)
                lag = publisher.version - version
                policy_lags.append(lag)
                outstanding = channel.outstanding()
                if ledger is not None:
                    ledger.note_ingest(t_ing, time.time(), steps, version,
                                  lag, seq, shard)
                if hub is not None and n_shards > 1:
                    # per-shard ingest heartbeat: a cold shard names a
                    # wedged actor (the stable assignment), without any
                    # device sync — counter + beat are host-side
                    hub.counter("replay_shard_ingest_total", shard=shard)
                    hub.gauge("replay_shard_ingest_seq", seq, shard=shard)
                    hub.beat(f"replay_shard{shard}")
                if hub is not None:
                    # gauges keep the PR 16 last-value semantics; the
                    # histograms add mid-run p50/p99/max to /metrics and
                    # the rings add history to /series — same samples,
                    # three read paths
                    hub.gauge("policy_lag", lag)
                    hub.gauge("replay_lag", outstanding)
                    hub.observe("policy_lag", lag)
                    hub.observe("replay_lag", outstanding)
                    hub.series("policy_lag", lag)
                    hub.series("replay_lag", outstanding)
                    hub.beat("learner")
                progressed = True
                check_stop()
            drain_results()
            if (checkpoint_every and checkpoint_fn is not None
                    and len(drained) - last_ckpt >= checkpoint_every):
                last_ckpt = len(drained)
                checkpoint_fn(state, buffers, len(drained))
            if bursts < allowance():
                verify_pending()   # may rollback + rebind the carries
                b_idx = bursts     # 0-based index of this burst
                if fault_plan is not None and fault_plan.fire(
                        "nan_grads", b_idx) is not None:
                    # async nan_grads is BURST-keyed: poison the state
                    # entering this burst; the deferred flag catches it
                    # one burst later and the guard restores
                    state = state.replace(
                        actor_params=poison_tree(state.actor_params))
                if hub is not None:
                    hub.note_thread_phase("learner", "learn_burst")
                t_burst = time.time()

                def dispatch_burst():
                    if fault_plan is not None and fault_plan.fire(
                            "learner_transient", b_idx) is not None:
                        raise TransientDispatchError(
                            f"injected transient at learn-burst {b_idx}")
                    with (timer.phase("learn_dispatch") if timer
                          else _noop()):
                        # R8 disabled below: same invariant as the
                        # actor's rollout dispatch — the sharded
                        # learn_burst wrapper takes dispatch_lock
                        # itself (dp.py)
                        return pddpg.learn_burst(state, buffers)  # gsc-lint: disable=R8 -- wrapper holds dispatch_lock

                if guarded:
                    # the transient class retries with backoff (the
                    # fault fires at entry, before anything dispatches,
                    # so a re-run consumes nothing)
                    state, last_metrics = call_with_retry(
                        dispatch_burst, retry_policy or RetryPolicy(),
                        on_retry=lambda attempt, exc, delay: recover(
                            len(drained), site="learner", action="retry",
                            fault=type(exc).__name__, attempt=attempt,
                            detail=f"learn-burst {b_idx}: {exc} "
                                   f"(backoff {delay:.2f}s)"))
                else:
                    state, last_metrics = dispatch_burst()
                bursts += 1
                if guard is not None and hasattr(last_metrics, "get"):
                    flag = last_metrics.get("state_finite")
                    if flag is not None:
                        pending_verify = (b_idx, flag)
                if ledger is not None:
                    ledger.note_burst(t_burst, time.time(), bursts)
                if hub is not None:
                    hub.beat("learner")
                if cfg.throttle_s:
                    time.sleep(cfg.throttle_s)
                if on_burst is not None:
                    on_burst(bursts, state, last_metrics)
                maybe_publish()
                progressed = True
            if not progressed:
                if not actors_alive() and channel.outstanding() == 0:
                    supervise()   # a just-queued failure may restart
                    if actors_alive() or channel.outstanding():
                        continue
                    if supervisor.unrun() and not stop_event.is_set():
                        # orphans with no live owner: respawn a cleanly-
                        # exited actor to drain them (degraded actors
                        # stay dead); with every actor past its budget,
                        # raise — never hang, never silently under-run
                        cand = [a for a in range(n_actors)
                                if a not in supervisor.dead]
                        if cand:
                            recover(len(drained), site="actor",
                                    action="restart", fault=None,
                                    detail=f"actor {cand[0]} respawned "
                                           f"to drain "
                                           f"{supervisor.unrun()} "
                                           f"orphaned episode(s)")
                            spawn_actor(cand[0], suffix="-orphans")
                            continue
                        raise RuntimeError(
                            f"async fleet exhausted: every actor is "
                            f"past its restart budget "
                            f"({supervisor.restart_budget}) with "
                            f"{supervisor.unrun()} episode(s) unrun"
                        ) from supervisor.errors[-1]
                    break
                if hub is not None:
                    hub.note_thread_phase("learner", "idle")
                    hub.beat("learner")   # an idle learner is not wedged
                t0 = time.perf_counter()
                channel.wait_for_data(cfg.idle_wait_s)
                waited = time.perf_counter() - t0
                learner_idle_acc[0] += waited
                if timer is not None:
                    timer.add("learner_idle", waited)
    finally:
        stop_event.set()
        channel.stop()
        for t in threads:
            t.join(timeout=30.0)
    drain_results()
    # final deferred verdict: with rollback on, the returned state is
    # ALWAYS verified — a burst poisoned at the very end restores here,
    # so preemption snapshots and final checkpoints never hold a NaN
    verify_pending()
    # graceful drain: nothing in flight, nothing lost, no future hung
    jax.block_until_ready((state, buffers))
    wall = time.perf_counter() - t_start
    idle_s = learner_idle_acc[0]
    if timer is not None:
        idle_s = (timer.summary().get("learner_idle")
                  or {}).get("total_s", idle_s)
    lag_sorted = sorted(policy_lags)
    pct = lambda q: (lag_sorted[min(int(q * len(lag_sorted)),  # noqa: E731
                                    len(lag_sorted) - 1)]
                     if lag_sorted else 0)
    actor_fracs = [round(w / wall, 4) if wall > 0 else 0.0
                   for w in actor_wait_s]
    info = {
        "actors": n_actors,
        "episodes_drained": len(drained),
        "produced_steps": channel.produced_steps,
        "ingested_steps": channel.ingested_steps,
        "transitions_lost": (channel.produced_steps
                             - channel.ingested_steps),
        "bursts": bursts,
        "publishes": publishes,
        "published_version": publisher.version,
        "max_staleness": max_stale,
        "max_replay_lag": channel.max_observed_lag,
        "policy_lag_max": max(policy_lags) if policy_lags else 0,
        "policy_lag_mean": (round(float(np.mean(policy_lags)), 4)
                            if policy_lags else 0.0),
        "policy_lag_p50": pct(0.50),
        "policy_lag_p99": pct(0.99),
        "wall_s": round(wall, 4),
        "learner_idle_s": round(idle_s, 4),
        "learner_idle_frac": round(idle_s / wall, 4) if wall > 0 else 0.0,
        "actor_idle_fracs": actor_fracs,
        "actor_idle_frac": max(actor_fracs) if actor_fracs else 0.0,
        "ring_shards": n_shards,
        "mesh": plan.describe() if plan is not None else None,
        # self-healing ledger (all zero on a clean run; the chaos stage
        # and bench_diff's informational keys read these)
        "actor_restarts": supervisor.total_restarts(),
        "actors_degraded": len(supervisor.dead),
        "blocks_quarantined": blocks_quarantined,
        "steps_quarantined": steps_quarantined,
        "rollbacks": guard.rollbacks if guard is not None else 0,
        # AOT-mined collective count on the ingest hot path; the prewarm
        # RAISES if it is ever nonzero, so a plan run always reports 0
        "ingest_collectives": ingest_collectives,
    }
    if hub is not None:
        # live probes made way for final plain gauges (a post-run scrape
        # must read the run's verdict, not a stale wall-clock fraction)
        hub.drop_live_gauge("learner_idle_frac")
        hub.gauge("learner_idle_frac", info["learner_idle_frac"])
        hub.series("learner_idle_frac", info["learner_idle_frac"])
        for a, frac in enumerate(actor_fracs):
            hub.drop_live_gauge("actor_idle_frac", actor=a)
            hub.gauge("actor_idle_frac", frac, actor=a)
            hub.series("actor_idle_frac", frac, actor=a)
        hub.gauge("actor_policy_version", publisher.version)
        # ring residency accounting: global bytes vs THIS host's
        # addressable-shard bytes (buffer_nbytes(local=True)) — under a
        # dp-sharded ring on a multi-host pod the local gauge is the
        # true per-host HBM spend; on one host they coincide.  Metadata
        # reads only, no device sync.
        hub.gauge("replay_ring_bytes", buffer_nbytes(buffers))
        hub.gauge("replay_ring_local_bytes",
                  buffer_nbytes(buffers, local=True))
        hub.gauge("replay_ring_shards", n_shards)
        if ledger is not None:
            ledger.flush_deferred(hub)
    return AsyncResult(state=state, buffers=buffers,
                       episodes=drained, info=info)


class _noop:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
