"""Distributed scaling layer: meshes, shardings, data-parallel training."""
from .dp import ParallelDDPG
from .mesh import (force_virtual_cpu, make_mesh, put_replicated,
                   put_sharded, replicated, sharded_axis0)

__all__ = ["ParallelDDPG", "force_virtual_cpu", "make_mesh",
           "put_replicated", "put_sharded",
           "replicated", "sharded_axis0"]
