"""Distributed scaling layer: meshes, shardings, data-parallel training."""
from .dp import ParallelDDPG
from .mesh import (force_virtual_cpu, make_mesh, put_replicated,
                   put_sharded, replicated, sharded_axis0)
from .partition import (ShardingPlan, make_shard_and_gather_fns,
                        make_train_mesh, match_partition_rules,
                        parse_mesh_shape, sharded_rules, spec_summary,
                        tp_rules)

__all__ = ["ParallelDDPG", "force_virtual_cpu", "make_mesh",
           "put_replicated", "put_sharded",
           "replicated", "sharded_axis0",
           "ShardingPlan", "make_shard_and_gather_fns", "make_train_mesh",
           "match_partition_rules", "parse_mesh_shape", "sharded_rules",
           "spec_summary", "tp_rules"]
