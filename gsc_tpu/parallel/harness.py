"""Chunked-episode measurement harness shared by the throughput/quality
tools (tools/learning_curve.py, tools/quality_sweep.py).

Episodes execute as several shorter ``rollout_episodes`` device calls
(the TPU operating mode — see ParallelDDPG.rollout_episodes) with the
end-of-episode learn burst, and per-episode metrics are aggregated over
ALL chunks: ``episodic_return`` sums across chunks and the success ratio
averages them — a single chunk's stats cover only that chunk's steps, so
reading the last chunk would score episodes on an end-of-episode slice.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def run_chunked_episodes(pddpg, topo, episode_traffic: Callable,
                         state, buffers, episodes: int, episode_steps: int,
                         chunk: int, seed: int,
                         on_episode: Optional[Callable] = None
                         ) -> Tuple[object, object, list, list]:
    """Train for ``episodes`` full episodes; returns
    (state, buffers, per-episode returns, per-episode success ratios).

    ``episode_traffic(ep)`` supplies the [B]-stacked TrafficSchedule for
    episode ``ep``; ``on_episode(ep, ret, succ, learn_metrics)`` is called
    after each episode's learn burst."""
    assert episode_steps % chunk == 0, (episode_steps, chunk)
    returns, succ = [], []
    for ep in range(episodes):
        traffic = episode_traffic(ep)
        env_states, obs = pddpg.reset_all(
            jax.random.fold_in(jax.random.PRNGKey(seed + 2), ep),
            topo, traffic)
        ep_ret = 0.0
        ep_succ = []
        for c in range(episode_steps // chunk):
            start = jnp.int32(ep * episode_steps + c * chunk)
            state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
                state, buffers, env_states, obs, topo, traffic, start, chunk)
            ep_ret += float(stats["episodic_return"])
            ep_succ.append(float(stats["mean_succ_ratio"]))
        state, metrics = pddpg.learn_burst(state, buffers)
        returns.append(ep_ret)
        succ.append(sum(ep_succ) / len(ep_succ))
        if on_episode is not None:
            on_episode(ep, ep_ret, succ[-1], metrics)
    return state, buffers, returns, succ
