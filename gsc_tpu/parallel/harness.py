"""Chunked-episode measurement harness shared by the throughput/quality
tools (tools/learning_curve.py, tools/quality_sweep.py).

Episodes execute as several shorter fused ``chunk_step`` device calls
(the TPU operating mode — see ParallelDDPG.rollout_episodes for the
chunking contract), the LAST one carrying the end-of-episode learn burst
in the same device program, and per-episode metrics are aggregated over
ALL chunks: ``episodic_return`` sums across chunks and the success ratio
averages them — a single chunk's stats cover only that chunk's steps, so
reading the last chunk would score episodes on an end-of-episode slice.

With ``hub`` (a :class:`gsc_tpu.obs.MetricsHub`) the harness streams
replica-resolved telemetry: per-replica episode returns and replay-shard
fill as gauges tagged ``replica=<i>``, plus one ``harness_episode`` event
per episode — a collapsing replica or a starved replay shard is invisible
in the cross-replica means the quality tools report.  ``timer`` (a
``PhaseTimer``) attributes the chunk-dispatch loop vs the metric-sync wall
exactly like the single-env trainer's dispatch/drain phases.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def run_chunked_episodes(pddpg, topo, episode_traffic: Callable,
                         state, buffers, episodes: int, episode_steps: int,
                         chunk: int, seed: int,
                         on_episode: Optional[Callable] = None,
                         step_offset: int = 0,
                         hub=None, timer=None,
                         topo_names: Optional[list] = None,
                         learn_names: Optional[list] = None
                         ) -> Tuple[object, object, list, list, list]:
    """Train for ``episodes`` full episodes; returns (state, buffers,
    per-episode returns, per-episode MEAN success ratios, per-episode
    FINAL-step success ratios).  The mean averages every step of the
    episode; the final-step value is the end-of-episode slice that the
    Trainer's ``final_succ_ratio`` and the historical quality bars
    (BENCH_NOTES: 0.48 -> 0.64) report — compare against the right one.

    ``episode_traffic(ep)`` supplies the [B]-stacked TrafficSchedule for
    episode ``ep``; ``on_episode(ep, ret, succ, learn_metrics)`` is called
    after each episode's learn burst.

    ``step_offset`` is the GLOBAL step of this call's first rollout step —
    callers that drive the harness one episode at a time (e.g.
    Trainer.train_parallel) must pass ``ep * episode_steps``, or the
    agent's warmup gate (global_step < nb_steps_warmup_critic selects
    random actions) would restart at 0 every episode and the policy would
    never act.

    ``topo_names`` ([B] per-replica topology names, mixed-topology runs):
    the hub additionally gets per-topology return gauges (tag
    ``topology=<name>``, mean over that topology's replicas) and the
    ``harness_episode`` event carries the per-replica ``topology`` list +
    a ``per_topology_return`` dict — a mixture member that collapses is
    visible by name, not just as one cold row in the replica vector.

    ``learn_names`` (topo_id -> name, from the driver): when the agent
    was built with a learn ledger (obs.learning), each episode's drained
    ``learn_signal`` — per-topology |TD| segments, Q moments, layer
    norms, replay fill — is emitted through the hub with these names;
    ledger-free agents produce no signal and nothing is emitted."""
    from ..obs.learning import emit_learn_signal
    from ..obs.trace import phase_span

    assert episode_steps % chunk == 0, (episode_steps, chunk)
    returns, succ, final_succ = [], [], []
    for ep in range(episodes):
        traffic = episode_traffic(ep)
        env_states, obs = pddpg.reset_all(
            jax.random.fold_in(jax.random.PRNGKey(seed + 2), ep),
            topo, traffic)
        chunk_stats = []
        n_chunks = episode_steps // chunk
        with phase_span("dispatch", timer, hub):
            for c in range(n_chunks):
                start = jnp.int32(step_offset + ep * episode_steps
                                  + c * chunk)
                # the FINAL chunk fuses the end-of-episode learn burst into
                # the same device program (ParallelDDPG.chunk_step) — no
                # host round-trip between the last rollout call and the
                # learner; results are bit-identical to the two-call path
                state, buffers, env_states, obs, stats, metrics = \
                    pddpg.chunk_step(state, buffers, env_states, obs, topo,
                                     traffic, start, chunk,
                                     learn=(c == n_chunks - 1))
                chunk_stats.append(stats)   # device scalars: convert AFTER
                # the episode is dispatched — a float() here would sync the
                # host every chunk and depress the measured wall rate
        with phase_span("drain", timer, hub):
            returns.append(sum(float(s["episodic_return"])
                               for s in chunk_stats))
            succ.append(sum(float(s["mean_succ_ratio"])
                            for s in chunk_stats) / len(chunk_stats))
            # end-of-episode slice: the final step's success ratio,
            # comparable to Trainer stats / the historical BENCH quality
            # bars
            final_succ.append(float(chunk_stats[-1]["final_succ_ratio"]))
        if hub is not None:
            # replica-resolved telemetry (the harness's own series — the
            # episodes_* counters belong to whoever drives the run).  The
            # event carries the GLOBAL episode index: per-episode drivers
            # (train_parallel) call with episodes=1 and a step_offset, so
            # the loop-local ep alone would stamp every record episode=0.
            global_ep = step_offset // episode_steps + ep
            per_rep = [np.asarray(s["per_replica_return"])
                       for s in chunk_stats if "per_replica_return" in s]
            rep_returns = (np.sum(per_rep, axis=0).tolist()
                           if per_rep else None)
            if rep_returns is not None:
                for r, v in enumerate(rep_returns):
                    hub.gauge("replica_return", v, replica=str(r))
            per_topo = None
            if rep_returns is not None and topo_names:
                groups = {}
                for name, v in zip(topo_names, rep_returns):
                    groups.setdefault(name, []).append(v)
                per_topo = {name: float(np.mean(vs))
                            for name, vs in groups.items()}
                for name, v in per_topo.items():
                    hub.gauge("topology_return", v, topology=name)
            if buffers is not None and hasattr(buffers, "size"):
                for r, fill in enumerate(np.asarray(buffers.size).tolist()):
                    hub.gauge("replica_replay_fill", fill, replica=str(r))
            # divergence-guard verdict for the episode: the rollout flags
            # (state entering each chunk) AND the learn burst's post-update
            # flag — all device scalars already synced by the drain above;
            # absent on fakes/legacy stats (None, not a false alarm)
            finite = None
            flags = [s["state_finite"] for s in chunk_stats
                     if "state_finite" in s]
            if metrics is not None and "state_finite" in metrics:
                flags.append(metrics["state_finite"])
            if flags:
                finite = bool(min(float(f) for f in flags) > 0)
            hub.event("harness_episode", episode=global_ep,
                      episodic_return=returns[-1],
                      mean_succ_ratio=succ[-1],
                      final_succ_ratio=final_succ[-1],
                      per_replica_return=rep_returns,
                      state_finite=finite,
                      # mixed-topology attribution; absent (not null-
                      # spammed) on homogeneous runs to keep the legacy
                      # event schema byte-stable
                      **({"topology": list(topo_names),
                          "per_topology_return": per_topo}
                         if topo_names else {}))
            signal = (metrics or {}).get("learn_signal") \
                if isinstance(metrics, dict) else None
            replay = chunk_stats[-1].get("replay") \
                if isinstance(chunk_stats[-1], dict) else None
            if signal is not None or replay is not None:
                # everything here was synced by the drain above — the
                # emit is pure host bookkeeping, never a device wait
                emit_learn_signal(hub, global_ep, signal=signal,
                                  replay=replay,
                                  segment_names=learn_names)
        if on_episode is not None:
            on_episode(ep, returns[-1], succ[-1], metrics)
    return state, buffers, returns, succ, final_succ
