"""Device mesh + sharding helpers.

The reference has no distributed execution of any kind (SURVEY.md §5: one
process, one env, CPU) — this module is the TPU-native scaling layer the
rebuild adds (BASELINE.json north_star): a 1-D ``dp`` mesh over which env
replicas, replay shards and learner batches are sharded, with parameters
replicated; XLA inserts the cross-chip collectives (grad psum) from the
sharding annotations.  The same code drives 1 chip, a v5e pod slice, or a
virtual ``xla_force_host_platform_device_count`` CPU mesh (tests/CI).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.extend  # explicit: clear_backends lives here, not on bare jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def force_virtual_cpu(n_devices: int) -> None:
    """Select an ``n_devices``-device virtual CPU platform — BEFORE any
    backend touch.

    The dry-run/CI entry point: call this before the first
    ``jax.devices()``/``jit`` of the process.  It sets
    ``xla_force_host_platform_device_count`` and switches
    ``jax_platforms`` to cpu via ``jax.config.update`` — the one order of
    operations that never initializes the default (possibly TPU) backend,
    whose init can hang indefinitely when the shared chip is wedged by an
    earlier faulted run (tests/conftest.py uses the same pattern).  If a
    CPU backend predating the flag is already live, falls back to
    ``clear_backends`` surgery."""
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        jax.extend.backend.clear_backends()
    if len(jax.devices()) < n_devices:
        raise ValueError(
            f"virtual CPU platform has {len(jax.devices())} devices, "
            f"need {n_devices}")


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all).

    If fewer devices exist than requested, falls back to a virtual CPU
    platform with ``n_devices`` host devices (the dry-run path for
    validating multi-chip shardings without hardware).  Note this probes
    the current backend first; dry-run entry points that must never touch
    the TPU should call ``force_virtual_cpu`` beforehand."""
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        force_virtual_cpu(n_devices)
        devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_axis0(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def put_replicated(tree, mesh: Mesh):
    """Replicate a pytree onto every device of the mesh."""
    return jax.device_put(tree, replicated(mesh))


def put_sharded(tree, mesh: Mesh, axis: str = "dp"):
    """Shard every leaf's leading (replica) axis across the mesh."""
    return jax.device_put(tree, sharded_axis0(mesh, axis))
