"""Device mesh + sharding helpers.

The reference has no distributed execution of any kind (SURVEY.md §5: one
process, one env, CPU) — this module is the TPU-native scaling layer the
rebuild adds (BASELINE.json north_star): a 1-D ``dp`` mesh over which env
replicas, replay shards and learner batches are sharded, with parameters
replicated; XLA inserts the cross-chip collectives (grad psum) from the
sharding annotations.  The same code drives 1 chip, a v5e pod slice, or a
virtual ``xla_force_host_platform_device_count`` CPU mesh (tests/CI).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.extend  # explicit: clear_backends lives here, not on bare jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def force_virtual_cpu(n_devices: int) -> None:
    """Select an ``n_devices``-device virtual CPU platform — BEFORE any
    backend touch.

    The dry-run/CI entry point: call this before the first
    ``jax.devices()``/``jit`` of the process.  It sets
    ``xla_force_host_platform_device_count`` and switches
    ``jax_platforms`` to cpu via ``jax.config.update`` — the one order of
    operations that never initializes the default (possibly TPU) backend,
    whose init can hang indefinitely when the shared chip is wedged by an
    earlier faulted run (tests/conftest.py uses the same pattern).  If a
    CPU backend predating the flag is already live, falls back to
    ``clear_backends`` surgery."""
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        jax.extend.backend.clear_backends()
    if len(jax.devices()) < n_devices:
        raise ValueError(
            f"virtual CPU platform has {len(jax.devices())} devices, "
            f"need {n_devices}")


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host runtime initialization (``jax.distributed.initialize``).

    Call ONCE per process, before any backend touch.  With no arguments,
    coordinates from the environment (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``, or the cloud autodetection
    jax ships).  After this, ``jax.devices()`` is GLOBAL across all
    processes and ``make_mesh()``/``make_hybrid_mesh()`` build pod-wide
    meshes; each process addresses only ``jax.local_devices()``.

    The reference has nothing comparable (SURVEY §5: one process, one CPU);
    this is the entry point BASELINE config 5's data-parallel v5e-16 run
    crosses hosts through."""
    kw = {}
    if coordinator is not None:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)


def make_hybrid_mesh(outer_axis: str = "dcn", axis: str = "dp") -> Mesh:
    """2-D (process, local-device) mesh: the outer axis crosses hosts (DCN
    on a multi-slice pod, ICI within a slice), the inner axis crosses each
    process's local chips.  Shard replicas over BOTH axes and keep
    parameters replicated: the gradient psum then reduces over ICI first
    and crosses DCN once per step — the standard DCN-last layout.

    Falls back to a [1, n] grid in single-process runs, so code written
    against (outer, inner) axis names runs unchanged on one host."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n_proc = jax.process_count()
    local = len(devs) // max(n_proc, 1)
    grid = np.asarray(devs).reshape(n_proc, local)
    return Mesh(grid, (outer_axis, axis))


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all).

    If fewer devices exist than requested, falls back to a virtual CPU
    platform with ``n_devices`` host devices (the dry-run path for
    validating multi-chip shardings without hardware).  Note this probes
    the current backend first; dry-run entry points that must never touch
    the TPU should call ``force_virtual_cpu`` beforehand."""
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        force_virtual_cpu(n_devices)
        devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_axis0(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def put_replicated(tree, mesh: Mesh):
    """Replicate a pytree onto every device of the mesh."""
    return jax.device_put(tree, replicated(mesh))


def put_sharded(tree, mesh: Mesh, axis: str = "dp"):
    """Shard every leaf's leading (replica) axis across the mesh."""
    return jax.device_put(tree, sharded_axis0(mesh, axis))
