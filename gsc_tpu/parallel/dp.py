"""Data-parallel DDPG over vmapped env replicas.

The scale-out path (BASELINE.json configs 2-5): B env replicas step in
lockstep under ``vmap`` (each with its own traffic sample and PRNG stream,
sharded across the ``dp`` mesh axis), feeding B per-replica replay shards;
the learner samples batches across all replicas and updates one replicated
parameter set — XLA turns the batch-mean gradient into a cross-chip psum
from the sharding annotations alone (no hand-written collectives).

Replica semantics mirror the single-env agent exactly (same warmup schedule,
noise, post-processing, episode-end learn burst); with B=1 this reduces to
``gsc_tpu.agents.DDPG``.

Precision: the replicated learner state stays f32 master state under every
policy (the inner DDPG owns that contract); ``init_buffers`` builds the
replica shards from ``DDPG.example_transition``, so a bf16 replay policy
halves EVERY shard and the cross-replica gathers of ``_sample_across`` /
``_sample_local`` move half the bytes per batch.  The batch-mean gradient
psum XLA inserts from the sharding annotations reduces f32 gradients — the
compute dtype never leaks into the cross-chip reduction.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..agents.buffer import (ReplayBuffer, buffer_add, flatten_transition,
                             restore_batch, transition_shapes)
from ..agents.ddpg import DDPG, DDPGState, donated_jit
from ..resilience.guard import all_finite
from ..config.schema import AgentConfig
from ..env.actions import action_mask
from ..env.env import ServiceCoordEnv


class ParallelDDPG:
    """B-replica data-parallel wrapper around the DDPG kernels."""

    def __init__(self, env: ServiceCoordEnv, agent: AgentConfig,
                 num_replicas: int, gnn_impl: str = None,
                 per_replica_topology: bool = False,
                 sample_mode: str = "across", donate: bool = False):
        if sample_mode not in ("across", "local"):
            raise ValueError(f"unknown sample_mode {sample_mode!r}")
        self.env = env
        self.agent = agent
        self.B = num_replicas
        self.sample_mode = sample_mode
        # the inner DDPG inherits ``donate`` so init() breaks the
        # target-params/params buffer aliasing that donation of the learner
        # state would otherwise trip over (double donation)
        self.ddpg = DDPG(env, agent, gnn_impl=gnn_impl, donate=donate)
        # ``donate=True`` aliases the replay shards into the rollout call,
        # so XLA appends transitions to the multi-GB replay in place
        # instead of copying it every chunk call, and the learner state
        # into the learn burst / fused chunk step.  ``obs`` and env states
        # are never donated here: their leaves can legitimately share
        # device buffers, which XLA rejects as double donation.  Callers
        # must treat donated arguments as CONSUMED (always rebind from the
        # return) — the training loops do; comparison-style double-calls
        # on the same inputs must keep the default.
        if donate:
            cls = type(self)
            self.rollout_episodes = donated_jit(
                self, cls.rollout_episodes, static_argnums=(0, 8),
                donate_argnums=(2,))
            self.learn_burst = donated_jit(
                self, cls.learn_burst, static_argnums=(0,),
                donate_argnums=(1,))
            self.chunk_step = donated_jit(
                self, cls.chunk_step, static_argnums=(0, 8, 9),
                donate_argnums=(1, 2))
        # With per_replica_topology, ``topo`` arguments carry a leading [B]
        # axis (build with topology.stack_topologies) and every replica
        # trains on its own network — topology-generalization pressure in
        # ONE scan, beyond the reference's serial per-episode swapping
        # (gym_env.py:103-128).
        self.per_replica_topology = per_replica_topology
        self._t_ax = 0 if per_replica_topology else None

    # ----------------------------------------------------------------- init
    def init(self, rng, sample_obs) -> DDPGState:
        """Replicated learner state (init from a single-replica obs)."""
        return self.ddpg.init(rng, sample_obs)

    def init_buffers(self, sample_obs,
                     num_replicas: int = None) -> ReplayBuffer:
        """Per-replica replay shards: leaves [B, capacity, ...]; capacity is
        mem_limit / B (floored at 1) so TOTAL memory matches the single-env
        agent's budget regardless of replica count — sampling is
        with-replacement, so small per-shard capacities stay valid.

        ``num_replicas`` overrides the leading axis for multi-PROCESS runs:
        each process allocates only its local shard (global B still sizes
        the per-replica capacity) and converts it with
        ``host_local_array_to_global_array`` — materializing the global
        buffer on one device first would transiently hold process_count
        times the per-chip replay budget."""
        cap = max(self.agent.mem_limit // self.B, 1)
        b = self.B if num_replicas is None else num_replicas
        example = self.ddpg.example_transition(sample_obs)
        data = jax.tree_util.tree_map(
            lambda x: jnp.zeros((b, cap) + jnp.shape(x),
                                jnp.asarray(x).dtype),
            flatten_transition(example))
        return ReplayBuffer(data=data, pos=jnp.zeros(b, jnp.int32),
                            size=jnp.zeros(b, jnp.int32),
                            shapes=transition_shapes(example))

    @partial(jax.jit, static_argnums=0)
    def reset_all(self, rng, topo, traffic):
        """vmap env.reset across replicas (traffic batched [B, ...])."""
        keys = jax.random.split(rng, self.B)
        return jax.vmap(self.env.reset, in_axes=(0, self._t_ax, 0))(
            keys, topo, traffic)

    # -------------------------------------------------------------- rollout
    def _rollout_body(self, state: DDPGState, buffers: ReplayBuffer,
                      env_states, obs, topo, traffic,
                      episode_start_step, num_steps: int = None) -> Tuple[
                          DDPGState, ReplayBuffer, Any, Any,
                          Dict[str, jnp.ndarray]]:
        """Replica rollout scan shared by ``rollout_episodes`` and the
        fused ``chunk_step`` (traced inside their jits)."""
        from ..env.permutation import ShuffleOps
        if (self.agent.shuffle_nodes and num_steps is not None
                and num_steps % self.agent.episode_steps != 0):
            raise ValueError(
                "chunked rollouts (num_steps < episode_steps) are "
                "incompatible with shuffle_nodes: each chunk call opens a "
                "fresh permutation frame, which is only correct at episode "
                "boundaries — disable shuffle_nodes or roll out whole "
                "episodes")
        rng, sub = jax.random.split(state.rng)
        shuffle = ShuffleOps(self.agent, self.env.limits)
        # per-replica node permutations, fresh each step, via the same
        # ShuffleOps protocol as the single-env agent
        sub, k0 = jax.random.split(sub)
        perms0 = jax.vmap(shuffle.init_perm)(jax.random.split(k0, self.B))
        obs = jax.vmap(shuffle.permute_obs)(obs, perms0)

        def one_step(es, ob, perm, buf, tr, tp, key, i):
            mask = action_mask(tp.node_mask, self.env.limits.num_sfcs,
                               self.env.limits.max_sfs)
            step_mask = shuffle.step_mask(ob, mask, perm)
            action = self.ddpg.choose_action(
                state.actor_params, ob, step_mask, episode_start_step + i, key)
            action = self.env.process_action(action)
            es, next_ob, reward, done, info = self.env.step(
                es, tp, tr, shuffle.env_action(action, perm))
            next_ob, next_perm = shuffle.advance(
                jax.random.fold_in(key, 1), next_ob, perm)
            buf = buffer_add(buf, {
                "obs": ob, "next_obs": next_ob, "action": action,
                "reward": reward, "done": done.astype(jnp.float32)})
            stats = {"reward": reward, "succ_ratio": info["succ_ratio"],
                     "avg_e2e_delay": info["avg_e2e_delay"]}
            return es, next_ob, next_perm, buf, stats

        def step_fn(carry, i):
            env_states, obs, perms, buffers = carry
            keys = jax.random.split(jax.random.fold_in(sub, i), self.B)
            env_states, obs, perms, buffers, stats = jax.vmap(
                one_step, in_axes=(0, 0, 0, 0, 0, self._t_ax, 0, None))(
                    env_states, obs, perms, buffers, traffic, topo, keys, i)
            return (env_states, obs, perms, buffers), stats

        T = self.agent.episode_steps if num_steps is None else num_steps
        (env_states, obs, _, buffers), stats = jax.lax.scan(
            step_fn, (env_states, obs, perms0, buffers), jnp.arange(T))
        # stats leaves: [T, B]
        episode_stats = {
            "episodic_return": stats["reward"].sum(0).mean(),
            "mean_succ_ratio": stats["succ_ratio"].mean(),
            "mean_e2e_delay": stats["avg_e2e_delay"].mean(),
            "final_succ_ratio": stats["succ_ratio"][-1].mean(),
            # [B] per-replica returns ride along for telemetry: the obs
            # hub tags replica-resolved gauges from them (a collapsing
            # replica is invisible in the cross-replica mean)
            "per_replica_return": stats["reward"].sum(0),
            # divergence guardrail over the (replicated) learner state
            # entering the chunk — same contract as DDPG._rollout_body;
            # the post-update flag rides in the learn metrics via the
            # shared _learn_burst
            "state_finite": all_finite(state),
        }
        return (state.replace(rng=rng), buffers, env_states, obs,
                episode_stats)

    @partial(jax.jit, static_argnums=(0, 8))
    def rollout_episodes(self, state: DDPGState, buffers: ReplayBuffer,
                         env_states, obs, topo, traffic,
                         episode_start_step, num_steps: int = None) -> Tuple[
                             DDPGState, ReplayBuffer, Any, Any,
                             Dict[str, jnp.ndarray]]:
        """One episode on every replica: scan over steps of a vmapped
        (action -> env.step -> buffer.add) body.  Parameters are shared
        (replicated); env state, obs, buffers and traffic carry the leading
        [B] replica axis.

        ``num_steps`` (static) overrides the scan length so an episode can be
        split into several shorter device calls (carry env_states/obs/buffers
        across calls, pass the global step of the chunk start as
        ``episode_start_step``).  Long single-call scans (200 steps x 100
        engine substeps) exceed the TPU runtime's per-call limits; 25-50-step
        chunks are the validated operating range.  Chunked resumption assumes
        ``shuffle_nodes`` is off (default): with shuffling on, each call
        opens a fresh permutation frame, which is only correct at episode
        boundaries."""
        return self._rollout_body(state, buffers, env_states, obs, topo,
                                  traffic, episode_start_step, num_steps)

    @partial(jax.jit, static_argnums=(0, 8, 9))
    def chunk_step(self, state: DDPGState, buffers: ReplayBuffer,
                   env_states, obs, topo, traffic, episode_start_step,
                   num_steps: int = None, learn: bool = False) -> Tuple[
                       DDPGState, ReplayBuffer, Any, Any,
                       Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Fused chunk rollout + (optional) learn burst in ONE device
        program — the replica-parallel analogue of ``DDPG.episode_step``.
        Drive an episode as ``episode_steps/chunk`` calls with
        ``learn=False`` and pass ``learn=True`` on the FINAL chunk: the
        end-of-episode learn burst then runs in the same program as the
        last rollout chunk, eliminating the host round-trip between them
        and letting XLA overlap the scan tail with the first gradient
        steps.  The op sequence is identical to ``rollout_episodes`` +
        ``learn_burst``, so results are bit-identical to the two-call
        path.  Returns ``learn_metrics=None`` when ``learn=False``."""
        state, buffers, env_states, obs, stats = self._rollout_body(
            state, buffers, env_states, obs, topo, traffic,
            episode_start_step, num_steps)
        metrics = None
        if learn:
            sampler = (self._sample_local if self.sample_mode == "local"
                       else self._sample_across)
            state, metrics = self.ddpg._learn_burst(
                state, lambda k: sampler(buffers, k))
        return state, buffers, env_states, obs, stats, metrics

    # ------------------------------------------------------------- learning
    def _sample_across(self, buffers: ReplayBuffer, key):
        """Uniform batch over (replica, slot) pairs from all shards —
        exact single-agent semantics, but the gather touches every shard:
        on a real dp mesh each inner-loop batch is cross-device traffic."""
        kb, ks = jax.random.split(key)
        bidx = jax.random.randint(kb, (self.agent.batch_size,), 0, self.B)
        sidx = jax.random.randint(ks, (self.agent.batch_size,), 0,
                                  jnp.maximum(buffers.size[bidx], 1))
        raw = jax.tree_util.tree_map(lambda d: d[bidx, sidx], buffers.data)
        return restore_batch(buffers.shapes, raw)

    def _sample_local(self, buffers: ReplayBuffer, key):
        """Shard-local stratified batch: batch_size/B (>=1) transitions from
        each replica's OWN shard, concatenated along the sharded axis — no
        cross-device gather; the batch-mean gradient reduces across shards
        through the psum XLA inserts from the sharding annotations.  Same
        uniform (replica, slot) marginal as _sample_across with the replica
        counts stratified; effective batch size rounds to B*max(batch//B,1)."""
        b_per = max(self.agent.batch_size // self.B, 1)
        keys = jax.random.split(key, self.B)

        def pick(shard, size, k):
            idx = jax.random.randint(k, (b_per,), 0, jnp.maximum(size, 1))
            return jax.tree_util.tree_map(lambda d: d[idx], shard)

        batch = jax.vmap(pick)(buffers.data, buffers.size, keys)
        raw = jax.tree_util.tree_map(
            lambda d: d.reshape((self.B * b_per,) + d.shape[2:]), batch)
        return restore_batch(buffers.shapes, raw)

    @partial(jax.jit, static_argnums=0)
    def learn_burst(self, state: DDPGState, buffers: ReplayBuffer
                    ) -> Tuple[DDPGState, Dict[str, jnp.ndarray]]:
        """episode_steps gradient steps over the replica shards
        (simple_ddpg.py:307-325 schedule), sampling per ``sample_mode``."""
        sampler = (self._sample_local if self.sample_mode == "local"
                   else self._sample_across)
        return self.ddpg._learn_burst(
            state, lambda k: sampler(buffers, k))
