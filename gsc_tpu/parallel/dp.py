"""Data-parallel DDPG over vmapped env replicas.

The scale-out path (BASELINE.json configs 2-5): B env replicas step in
lockstep under ``vmap`` (each with its own traffic sample and PRNG stream,
sharded across the ``dp`` mesh axis), feeding B per-replica replay shards;
the learner samples batches across all replicas and updates one replicated
parameter set — XLA turns the batch-mean gradient into a cross-chip psum
from the sharding annotations alone (no hand-written collectives).

Replica semantics mirror the single-env agent exactly (same warmup schedule,
noise, post-processing, episode-end learn burst); with B=1 this reduces to
``gsc_tpu.agents.DDPG``.

Precision: the replicated learner state stays f32 master state under every
policy (the inner DDPG owns that contract); ``init_buffers`` builds the
replica shards from ``DDPG.example_transition``, so a bf16 replay policy
halves EVERY shard and the cross-replica gathers of ``_sample_across`` /
``_sample_local`` move half the bytes per batch.  The batch-mean gradient
psum XLA inserts from the sharding annotations reduces f32 gradients — the
compute dtype never leaks into the cross-chip reduction.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..agents.buffer import (ReplayBuffer, buffer_add, flatten_transition,
                             restore_batch, transition_shapes)
from ..agents.ddpg import DDPG, DDPGState, donated_jit
from ..resilience.guard import all_finite
from ..config.schema import AgentConfig
from ..env.actions import action_mask
from ..env.env import ServiceCoordEnv


class ParallelDDPG:
    """B-replica data-parallel wrapper around the DDPG kernels."""

    def __init__(self, env: ServiceCoordEnv, agent: AgentConfig,
                 num_replicas: int, gnn_impl: str = None,
                 per_replica_topology: bool = False,
                 sample_mode: str = "across", donate: bool = False,
                 plan=None, learn_ledger=None):
        if sample_mode not in ("across", "local"):
            raise ValueError(f"unknown sample_mode {sample_mode!r}")
        self.env = env
        self.agent = agent
        self.B = num_replicas
        self.sample_mode = sample_mode
        self.donate = donate
        # ``plan`` (a partition.ShardingPlan): rebind the three dispatch
        # entry points with EXPLICIT in_shardings/out_shardings over the
        # plan's dp x mp mesh — replicas/replay over the whole grid,
        # learner state per the plan's partition rules.  plan=None is the
        # no-op fallback: the code path below is byte-identical to the
        # pre-partition stack.
        self.plan = plan
        if plan is not None and num_replicas % plan.n_devices != 0:
            raise ValueError(
                f"num_replicas ({num_replicas}) must be divisible by the "
                f"mesh device count ({plan.n_devices}, mesh "
                f"{plan.describe()}) for an even replica sharding")
        # the inner DDPG inherits ``donate`` so init() breaks the
        # target-params/params buffer aliasing that donation of the learner
        # state would otherwise trip over (double donation), and the
        # learn-ledger spec so the shared _learn_burst folds the
        # per-topology TD segments into the replica dispatch too
        self.ddpg = DDPG(env, agent, gnn_impl=gnn_impl, donate=donate,
                         learn_ledger=learn_ledger)
        # ``donate=True`` aliases the replay shards into the rollout call,
        # so XLA appends transitions to the multi-GB replay in place
        # instead of copying it every chunk call, and the learner state
        # into the learn burst / fused chunk step.  ``obs`` and env states
        # are never donated here: their leaves can legitimately share
        # device buffers, which XLA rejects as double donation.  Callers
        # must treat donated arguments as CONSUMED (always rebind from the
        # return) — the training loops do; comparison-style double-calls
        # on the same inputs must keep the default.
        # With per_replica_topology, ``topo`` arguments carry a leading [B]
        # axis (build with topology.stack_topologies) and every replica
        # trains on its own network — topology-generalization pressure in
        # ONE scan, beyond the reference's serial per-episode swapping
        # (gym_env.py:103-128).
        self.per_replica_topology = per_replica_topology
        self._t_ax = 0 if per_replica_topology else None
        if plan is not None:
            self._bind_sharded_dispatch()
        elif donate:
            cls = type(self)
            self.rollout_episodes = donated_jit(
                self, cls.rollout_episodes, static_argnums=(0, 8),
                donate_argnums=(2,))
            self.learn_burst = donated_jit(
                self, cls.learn_burst, static_argnums=(0, 3),
                donate_argnums=(1,))
            self.chunk_step = donated_jit(
                self, cls.chunk_step, static_argnums=(0, 8, 9),
                donate_argnums=(1, 2))

    def _bind_sharded_dispatch(self):
        """Rebind chunk_step / rollout_episodes / learn_burst as sharded
        jits: explicit ``in_shardings``/``out_shardings`` over the plan's
        mesh (donation folded in when ``donate=True``).

        The learner-state sharding tree needs the state's pytree
        structure, which only exists once a state does — so the jits are
        built LAZILY on the first dispatch and cached; later calls (and
        every shard/gather move, which is plain ``device_put``) reuse
        them without retracing.  ``jax.jit`` rejects kwargs when
        in_shardings is given, so the public wrappers keep the historic
        keyword signature and forward positionally."""
        from functools import partial as _partial

        from jax.sharding import NamedSharding

        cls = type(self)
        plan = self.plan
        data, rep = plan.data_sharding, plan.replicated
        topo_sh = data if self.per_replica_topology else rep
        # the tp book keeps the learner state RESIDENT-sharded through
        # the compiled program; the entry-placement counter below is the
        # no-layout-move witness tests assert on (exactly one placement
        # per caller-fresh state, zero on the steady-state dispatch path)
        tp = plan.resident_sharded
        self.entry_state_moves = 0
        fns = {}
        # the async path dispatches rollouts from MANY actor threads:
        # the build-once dict fill, the placement memo and the
        # entry-move counter are the binding's only shared mutable
        # state, so one lock makes every wrapper thread-safe (run_async
        # additionally pre-builds via sharded_lowerable before any
        # actor thread exists, so the lock is uncontended steady-state)
        import threading as _threading
        bind_lock = _threading.Lock()
        # XLA:CPU multi-device executions rendezvous their partitions at
        # every collective.  Enqueue order onto the per-device work
        # queues follows the Python call, and the GIL is released inside
        # it — so two threads dispatching multi-device programs
        # concurrently can interleave their per-device enqueue loops
        # inconsistently (device 0 sees program B first, devices 1..3
        # see program A first) and BOTH programs deadlock at their first
        # rendezvous, each holding the devices the other needs.
        # Observed live on the forced-device async x mesh path: two
        # actor threads' rollout dispatches stuck with complementary
        # arrival sets.  Serializing the dispatch CALL (not the
        # execution — dispatch is async; the call returns after
        # enqueue) makes the per-device queue order globally consistent,
        # which is deadlock-free by construction.  run_async shares
        # this lock for its AOT-compiled ingest dispatch.
        self.dispatch_lock = _threading.Lock()

        def build(state):
            # Two residency designs share this binding:
            #
            # replicated/sharded books (PR 8, ZeRO-style): the learner
            # state RESIDES sharded between dispatches (params + Adam
            # moments split over mp per the plan's rules — the
            # HBM-residency win), but the COMPILED PROGRAM only ever
            # sees it replicated: the wrappers below allgather it with
            # an eager ``device_put`` on the way in and slice it back to
            # shards on the way out (pure layout moves, never a
            # retrace).  With no mp annotation inside the program, the
            # partitioned executable is identical for every carving of
            # the same device count — which is exactly what makes the
            # final learner state BIT-identical across mesh shapes.
            #
            # tp book (true tensor-parallel compute): the state's
            # in_/out_shardings ARE the plan's partition layout, so it
            # stays sharded THROUGH the program — the entry-allgather /
            # exit-slice moves are deleted (the real HBM + interconnect
            # win) and GSPMD psums the partial products of the sharded
            # contractions.  The psum reduces shards in a
            # carving-dependent order (~1e-7 drift per mp size per
            # gradient step), so tp runs are accepted under the
            # bench_diff tolerance bands, never by digest.
            ss = plan.state_shardings(state)
            fns["_state_shardings"] = ss
            fns["_ss_leaves"] = jax.tree_util.tree_leaves(
                ss, is_leaf=lambda x: isinstance(x, NamedSharding))
            state_sh = ss if tp else rep
            # dynamic args of all three entry points, in order: state,
            # buffers, env_states, obs, topo, traffic, start (static
            # self/num_steps/learn are excluded from in_shardings).  A
            # per-replica topology carries the [B] replica axis, so it
            # shards like the other batch data; the historic single-
            # topology path keeps it replicated.
            arg_sh = (state_sh, data, data, data, topo_sh, data, rep)

            def shard_jit(method, static, donate_pos, n_in, out_sh):
                fn = getattr(method, "__wrapped__", method)
                return _partial(jax.jit(
                    fn, static_argnums=static,
                    donate_argnums=donate_pos if self.donate else (),
                    in_shardings=arg_sh[:n_in], out_shardings=out_sh),
                    self)

            fns["chunk_step"] = shard_jit(
                cls.chunk_step, (0, 8, 9), (1, 2), 7,
                (state_sh, data, data, data, rep, rep))
            fns["rollout_episodes"] = shard_jit(
                cls.rollout_episodes, (0, 8), (2,), 7,
                (state_sh, data, data, data, rep))
            fns["learn_burst"] = shard_jit(
                cls.learn_burst, (0, 3), (1,), 2, (state_sh, rep))
            return fns

        def state_in(state):
            if not tp:
                # entry allgather: ss -> replicated (no-op for a state
                # that is already replicated, e.g. the first dispatch)
                return jax.device_put(state, rep)
            # tp: the state is resident in the program's own layout —
            # a caller-fresh tree (init, restore) is placed exactly
            # once; every carry rebound from our outputs already
            # matches and passes through UNTOUCHED (no device_put, no
            # allgather — the contract tests assert via the counter).
            # All-leaf check, not first-leaf: a host-rebuilt leaf (e.g.
            # state.replace(rng=...)) must re-place, or the jit would
            # reject the mismatched committed leaf.
            ss_leaves = fns["_ss_leaves"]
            leaves = jax.tree_util.tree_leaves(state)
            if len(leaves) == len(ss_leaves) and all(
                    getattr(l, "sharding", None) == s
                    for l, s in zip(leaves, ss_leaves)):
                return state
            with bind_lock:
                self.entry_state_moves += 1
            return jax.device_put(state, fns["_state_shardings"])

        def state_out(state):
            if tp:
                # already in the plan's residency via out_shardings —
                # returning it unmoved IS the deleted exit slice
                return state
            # exit slice: replicated -> the plan's sharded residency
            return jax.device_put(state, fns["_state_shardings"])

        # entry placement for the data/replicated pytrees: this jax
        # version does NOT auto-reshard committed arguments that mismatch
        # in_shardings, and callers legitimately hand over single-device
        # pytrees (reset_all outputs, host-staged traffic, a restored
        # replay) — an eager device_put is a no-op for an already-placed
        # carry (same buffers back, so donation still consumes the
        # original) and a layout move exactly once otherwise.  This is
        # what lets Trainer/harness code drive the sharded path with ZERO
        # call-site changes.  Carries the caller rebinds from our outputs
        # (buffers/env_states/obs) are already placed, so their device_put
        # is free; topo/traffic arrive as the SAME host object every chunk
        # call of an episode — a small keep-alive memo makes their
        # placement once-per-object instead of once-per-call.
        from collections import OrderedDict
        memo = OrderedDict()

        def put_once(tree, sh):
            key = id(tree)
            with bind_lock:
                hit = memo.get(key)
                if hit is not None and hit[0] is tree and hit[1] is sh:
                    return hit[2]
            out = jax.device_put(tree, sh)
            # the retained `tree` ref keeps the id from being recycled;
            # the bound keeps a long run from accumulating every
            # episode's host traffic
            with bind_lock:
                memo[key] = (tree, sh, out)
                while len(memo) > 8:
                    memo.popitem(last=False)
            return out

        def put_data(tree):
            # rebound carries (buffers/env_states/obs): placed after the
            # first call, so no memo — memoizing DONATED trees would pin
            # consumed buffers alive
            return jax.device_put(tree, data)

        # every dispatch (where a compile, or a recompile after cache
        # eviction, can happen) runs under the multi-device-CPU guard:
        # deserializing num_partitions>1 CPU executables from the
        # persistent compilation cache heap-corrupts or silently
        # miscomputes on this jax version (see partition.py) — the
        # in-memory executable is unaffected, so steady-state calls pay
        # two config reads and nothing else
        from .partition import no_persistent_compile_cache

        def built(name, state):
            # double-checked build: the lazy first-dispatch fill must not
            # race a second thread into a duplicate trace
            fn = fns.get(name)
            if fn is not None:
                return fn
            with bind_lock:
                if name not in fns:
                    build(state)
                return fns[name]

        def chunk_step(state, buffers, env_states, obs, topo, traffic,
                       episode_start_step, num_steps=None, learn=False):
            fn = built("chunk_step", state)
            with no_persistent_compile_cache(plan.mesh), \
                    self.dispatch_lock:
                out = fn(state_in(state), put_data(buffers),
                         put_data(env_states), put_data(obs),
                         put_once(topo, topo_sh), put_once(traffic, data),
                         jax.device_put(episode_start_step, rep),
                         num_steps, learn)
            return (state_out(out[0]),) + out[1:]

        def rollout_episodes(state, buffers, env_states, obs, topo,
                             traffic, episode_start_step, num_steps=None):
            fn = built("rollout_episodes", state)
            with no_persistent_compile_cache(plan.mesh), \
                    self.dispatch_lock:
                out = fn(state_in(state), put_data(buffers),
                         put_data(env_states), put_data(obs),
                         put_once(topo, topo_sh), put_once(traffic, data),
                         jax.device_put(episode_start_step, rep),
                         num_steps)
            return (state_out(out[0]),) + out[1:]

        def learn_burst(state, buffers):
            fn = built("learn_burst", state)
            with no_persistent_compile_cache(plan.mesh), \
                    self.dispatch_lock:
                out = fn(state_in(state), put_data(buffers))
            return (state_out(out[0]),) + out[1:]

        self.chunk_step = chunk_step
        self.rollout_episodes = rollout_episodes
        self.learn_burst = learn_burst
        # the plan-bound jits themselves, for AOT capture (obs.perf mines
        # the SHARDED executable's HLO — collective counts/bytes — next
        # to the carving-comparable plain capture)
        self._sharded_fns = fns
        self._sharded_build = build

    def sharded_lowerable(self, name: str, state):
        """The plan-bound jit actually dispatched for ``name`` (a
        ``functools.partial`` over a jit with explicit shardings), built
        from ``state`` if the lazy binding has not happened yet; ``None``
        without a plan.  Callers lower it AOT (``obs.perf.CostLedger``)
        to mine the PARTITIONED program's HLO — fusions and collective
        ops — which the unsharded class jit cannot show.  Lowering a
        multi-device CPU program must run under
        ``partition.no_persistent_compile_cache`` (same wart as the
        dispatch compiles)."""
        if self.plan is None:
            return None
        if name not in self._sharded_fns:
            self._sharded_build(state)
        return self._sharded_fns[name]

    # ----------------------------------------------------------------- init
    def init(self, rng, sample_obs) -> DDPGState:
        """Replicated learner state (init from a single-replica obs)."""
        return self.ddpg.init(rng, sample_obs)

    def init_buffers(self, sample_obs, num_replicas: int = None,
                     capacity: int = None) -> ReplayBuffer:
        """Per-replica replay shards: leaves [B, capacity, ...]; capacity is
        mem_limit / B (floored at 1) so TOTAL memory matches the single-env
        agent's budget regardless of replica count — sampling is
        with-replacement, so small per-shard capacities stay valid.

        ``num_replicas`` overrides the leading axis for multi-PROCESS runs:
        each process allocates only its local shard (global B still sizes
        the per-replica capacity) and converts it with
        ``host_local_array_to_global_array`` — materializing the global
        buffer on one device first would transiently hold process_count
        times the per-chip replay budget.

        ``capacity`` overrides the per-replica slot count outright — the
        async actors allocate chunk-sized SCRATCH rings this way (one
        rollout dispatch fills the ring exactly, so the handed-off block
        is the chunk's transitions in step order)."""
        cap = (int(capacity) if capacity is not None
               else max(self.agent.mem_limit // self.B, 1))
        b = self.B if num_replicas is None else num_replicas
        example = self.ddpg.example_transition(sample_obs)
        data = jax.tree_util.tree_map(
            lambda x: jnp.zeros((b, cap) + jnp.shape(x),
                                jnp.asarray(x).dtype),
            flatten_transition(example))
        return ReplayBuffer(data=data, pos=jnp.zeros(b, jnp.int32),
                            size=jnp.zeros(b, jnp.int32),
                            shapes=transition_shapes(example))

    @partial(jax.jit, static_argnums=0)
    def reset_all(self, rng, topo, traffic):
        """vmap env.reset across replicas (traffic batched [B, ...])."""
        keys = jax.random.split(rng, self.B)
        return jax.vmap(self.env.reset, in_axes=(0, self._t_ax, 0))(
            keys, topo, traffic)

    # -------------------------------------------------------------- rollout
    def _rollout_body(self, state: DDPGState, buffers: ReplayBuffer,
                      env_states, obs, topo, traffic,
                      episode_start_step, num_steps: int = None) -> Tuple[
                          DDPGState, ReplayBuffer, Any, Any,
                          Dict[str, jnp.ndarray]]:
        """Replica rollout scan shared by ``rollout_episodes`` and the
        fused ``chunk_step`` (traced inside their jits)."""
        from ..env.permutation import ShuffleOps
        if (self.agent.shuffle_nodes and num_steps is not None
                and num_steps % self.agent.episode_steps != 0):
            raise ValueError(
                "chunked rollouts (num_steps < episode_steps) are "
                "incompatible with shuffle_nodes: each chunk call opens a "
                "fresh permutation frame, which is only correct at episode "
                "boundaries — disable shuffle_nodes or roll out whole "
                "episodes")
        rng, sub = jax.random.split(state.rng)
        shuffle = ShuffleOps(self.agent, self.env.limits)
        # per-replica node permutations, fresh each step, via the same
        # ShuffleOps protocol as the single-env agent
        sub, k0 = jax.random.split(sub)
        perms0 = jax.vmap(shuffle.init_perm)(jax.random.split(k0, self.B))
        obs = jax.vmap(shuffle.permute_obs)(obs, perms0)

        def one_step(es, ob, perm, buf, tr, tp, key, i):
            mask = action_mask(tp.node_mask, self.env.limits.num_sfcs,
                               self.env.limits.max_sfs)
            step_mask = shuffle.step_mask(ob, mask, perm)
            action = self.ddpg.choose_action(
                state.actor_params, ob, step_mask, episode_start_step + i, key)
            action = self.env.process_action(action)
            es, next_ob, reward, done, info = self.env.step(
                es, tp, tr, shuffle.env_action(action, perm))
            next_ob, next_perm = shuffle.advance(
                jax.random.fold_in(key, 1), next_ob, perm)
            buf = buffer_add(buf, {
                "obs": ob, "next_obs": next_ob, "action": action,
                "reward": reward, "done": done.astype(jnp.float32),
                # per-replica network attribution: in mixed-topology
                # batches tp is this replica's topology slice, so its
                # topo_id is the mix-entry index
                "topo_idx": tp.topo_id})
            stats = {"reward": reward, "succ_ratio": info["succ_ratio"],
                     "avg_e2e_delay": info["avg_e2e_delay"]}
            return es, next_ob, next_perm, buf, stats

        def step_fn(carry, i):
            env_states, obs, perms, buffers = carry
            keys = jax.random.split(jax.random.fold_in(sub, i), self.B)
            env_states, obs, perms, buffers, stats = jax.vmap(
                one_step, in_axes=(0, 0, 0, 0, 0, self._t_ax, 0, None))(
                    env_states, obs, perms, buffers, traffic, topo, keys, i)
            return (env_states, obs, perms, buffers), stats

        T = self.agent.episode_steps if num_steps is None else num_steps
        (env_states, obs, _, buffers), stats = jax.lax.scan(
            step_fn, (env_states, obs, perms0, buffers), jnp.arange(T))
        # stats leaves: [T, B]
        episode_stats = {
            "episodic_return": stats["reward"].sum(0).mean(),
            "mean_succ_ratio": stats["succ_ratio"].mean(),
            "mean_e2e_delay": stats["avg_e2e_delay"].mean(),
            "final_succ_ratio": stats["succ_ratio"][-1].mean(),
            # [B] per-replica returns ride along for telemetry: the obs
            # hub tags replica-resolved gauges from them (a collapsing
            # replica is invisible in the cross-replica mean)
            "per_replica_return": stats["reward"].sum(0),
            # divergence guardrail over the (replicated) learner state
            # entering the chunk — same contract as DDPG._rollout_body;
            # the post-update flag rides in the learn metrics via the
            # shared _learn_burst
            "state_finite": all_finite(state),
        }
        if self.ddpg.learn_ledger is not None:
            # per-replica replay fill/age ([B] leaves), on device — same
            # ledger contract as the single-agent rollout
            from ..obs.learning import replay_stats
            episode_stats["replay"] = replay_stats(buffers)
        return (state.replace(rng=rng), buffers, env_states, obs,
                episode_stats)

    @partial(jax.jit, static_argnums=(0, 8))
    def rollout_episodes(self, state: DDPGState, buffers: ReplayBuffer,
                         env_states, obs, topo, traffic,
                         episode_start_step, num_steps: int = None) -> Tuple[
                             DDPGState, ReplayBuffer, Any, Any,
                             Dict[str, jnp.ndarray]]:
        """One episode on every replica: scan over steps of a vmapped
        (action -> env.step -> buffer.add) body.  Parameters are shared
        (replicated); env state, obs, buffers and traffic carry the leading
        [B] replica axis.

        ``num_steps`` (static) overrides the scan length so an episode can be
        split into several shorter device calls (carry env_states/obs/buffers
        across calls, pass the global step of the chunk start as
        ``episode_start_step``).  Long single-call scans (200 steps x 100
        engine substeps) exceed the TPU runtime's per-call limits; 25-50-step
        chunks are the validated operating range.  Chunked resumption assumes
        ``shuffle_nodes`` is off (default): with shuffling on, each call
        opens a fresh permutation frame, which is only correct at episode
        boundaries."""
        return self._rollout_body(state, buffers, env_states, obs, topo,
                                  traffic, episode_start_step, num_steps)

    @partial(jax.jit, static_argnums=(0, 8, 9))
    def chunk_step(self, state: DDPGState, buffers: ReplayBuffer,
                   env_states, obs, topo, traffic, episode_start_step,
                   num_steps: int = None, learn: bool = False) -> Tuple[
                       DDPGState, ReplayBuffer, Any, Any,
                       Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Fused chunk rollout + (optional) learn burst in ONE device
        program — the replica-parallel analogue of ``DDPG.episode_step``.
        Drive an episode as ``episode_steps/chunk`` calls with
        ``learn=False`` and pass ``learn=True`` on the FINAL chunk: the
        end-of-episode learn burst then runs in the same program as the
        last rollout chunk, eliminating the host round-trip between them
        and letting XLA overlap the scan tail with the first gradient
        steps.  The op sequence is identical to ``rollout_episodes`` +
        ``learn_burst``, so results are bit-identical to the two-call
        path.  Returns ``learn_metrics=None`` when ``learn=False``."""
        state, buffers, env_states, obs, stats = self._rollout_body(
            state, buffers, env_states, obs, topo, traffic,
            episode_start_step, num_steps)
        metrics = None
        if learn:
            sampler = (self._sample_local if self.sample_mode == "local"
                       else self._sample_across)
            state, metrics = self.ddpg._learn_burst(
                state, self._batch_sampler(sampler, buffers),
                constrain=self._state_constraint())
        return state, buffers, env_states, obs, stats, metrics

    # ------------------------------------------------------------- learning
    def _state_constraint(self):
        """Per-gradient-step learner-state re-pin for ``_learn_burst``:
        under a replicated/sharded plan the loop carry is
        constraint-gathered to replicated at the top of every step (see
        the sharded-dispatch ZeRO note), keeping every gradient step's
        math canonical.  Under the ``tp`` plan the pin is the PLAN'S OWN
        sharded layout instead — the constraint keeps GSPMD's fixpoint
        ON the tensor-parallel layout through steps 2..N and the
        back-edge, so every gradient step contracts sharded dims with
        psum accumulation (replacing the carry re-pin-to-replicated, not
        just dropping it: an unconstrained carry lets the fixpoint drift
        toward whatever layout minimizes the first step, changing the
        accepted numerics run to run).  None without a plan — the
        historic trace, byte for byte."""
        if self.plan is None:
            return None
        if self.plan.resident_sharded:
            plan = self.plan
            return lambda st: jax.lax.with_sharding_constraint(
                st, plan.state_shardings(st))
        rep = self.plan.replicated
        return lambda st: jax.lax.with_sharding_constraint(st, rep)

    def _batch_sampler(self, sampler, buffers: ReplayBuffer):
        """``sample_fn(key)`` for the learn burst.  Under a sharding plan
        the sampled batch is constraint-REPLICATED before any gradient
        math touches it: every batch contraction (loss mean, dW) then
        runs in canonical full-batch order identically on every device,
        so the learner state stays BIT-identical across mesh carvings —
        a batch left sharded would psum per-shard partial sums in a
        carving-dependent (dp-then-mp) order.  The gather this buys is
        one micro-batch per gradient step, orders of magnitude smaller
        than the replay shards that stay distributed.  The ``tp`` book
        keeps the SAME replicated-batch pin (the Megatron pattern:
        activations replicated/feature-sharded, weights sharded) — under
        tp it is the weight contractions, not the batch, that psum.
        Without a plan this is a no-op passthrough (the pre-partition
        stack verbatim)."""
        if self.plan is None:
            return lambda k: sampler(buffers, k)
        rep = self.plan.replicated
        return lambda k: jax.lax.with_sharding_constraint(
            sampler(buffers, k), rep)

    def _sample_across(self, buffers: ReplayBuffer, key):
        """Uniform batch over (replica, slot) pairs from all shards —
        exact single-agent semantics, but the gather touches every shard:
        on a real dp mesh each inner-loop batch is cross-device traffic."""
        kb, ks = jax.random.split(key)
        bidx = jax.random.randint(kb, (self.agent.batch_size,), 0, self.B)
        sidx = jax.random.randint(ks, (self.agent.batch_size,), 0,
                                  jnp.maximum(buffers.size[bidx], 1))
        raw = jax.tree_util.tree_map(lambda d: d[bidx, sidx], buffers.data)
        return restore_batch(buffers.shapes, raw)

    def _sample_local(self, buffers: ReplayBuffer, key):
        """Shard-local stratified batch: batch_size/B (>=1) transitions from
        each replica's OWN shard, concatenated along the sharded axis — no
        cross-device gather; the batch-mean gradient reduces across shards
        through the psum XLA inserts from the sharding annotations.  Same
        uniform (replica, slot) marginal as _sample_across with the replica
        counts stratified; effective batch size rounds to B*max(batch//B,1)."""
        b_per = max(self.agent.batch_size // self.B, 1)
        keys = jax.random.split(key, self.B)

        def pick(shard, size, k):
            idx = jax.random.randint(k, (b_per,), 0, jnp.maximum(size, 1))
            return jax.tree_util.tree_map(lambda d: d[idx], shard)

        batch = jax.vmap(pick)(buffers.data, buffers.size, keys)
        raw = jax.tree_util.tree_map(
            lambda d: d.reshape((self.B * b_per,) + d.shape[2:]), batch)
        return restore_batch(buffers.shapes, raw)

    @partial(jax.jit, static_argnums=(0, 3))
    def learn_burst(self, state: DDPGState, buffers: ReplayBuffer,
                    steps: int = None
                    ) -> Tuple[DDPGState, Dict[str, jnp.ndarray]]:
        """episode_steps gradient steps over the replica shards
        (simple_ddpg.py:307-325 schedule), sampling per ``sample_mode``.
        ``steps`` (static) overrides the burst length — the async
        learner's pacing knob over its externally-advancing ring."""
        sampler = (self._sample_local if self.sample_mode == "local"
                   else self._sample_across)
        return self.ddpg._learn_burst(
            state, self._batch_sampler(sampler, buffers),
            constrain=self._state_constraint(), steps=steps)
